"""Lookup (delta) join: arrangement sharing, delta-join algebra parity
with the hash join, stateless recovery.

Reference: `src/stream/src/executor/lookup.rs`,
`src/frontend/src/optimizer/plan_node/stream_delta_join.rs`.
"""
from risingwave_tpu.sql import Database


def ticks(db, n=3):
    for _ in range(n):
        db.tick()


def mk(delta: bool):
    db = Database()
    if delta:
        db.run("SET streaming_enable_delta_join TO true")
    db.run("CREATE TABLE users (uid BIGINT PRIMARY KEY, name VARCHAR)")
    db.run("CREATE TABLE orders (oid BIGINT PRIMARY KEY, uid BIGINT,"
           " amt BIGINT)")
    # the fk side needs an arrangement keyed by the join key — an index,
    # exactly the reference delta-join rule's requirement
    db.run("CREATE INDEX orders_by_uid ON orders (uid)")
    return db


JOIN_MV = ("CREATE MATERIALIZED VIEW j AS SELECT orders.oid, users.name,"
           " orders.amt FROM orders JOIN users ON orders.oid = oid")


class TestLookupJoin:
    def _drive(self, db):
        db.run("INSERT INTO users VALUES (1, 'ann'), (2, 'bo')")
        db.run("INSERT INTO orders VALUES (10, 1, 100), (11, 2, 200),"
               " (12, 3, 300)")
        ticks(db)

    def test_planned_as_lookup_join(self):
        db = mk(True)
        db.run("CREATE MATERIALIZED VIEW j AS SELECT o.amt, u.name "
               "FROM orders o JOIN users u ON o.uid = u.uid")
        names = [type(e).__name__ for e in _executors(db, "j")]
        assert "LookupJoinExecutor" in names, names

    def test_parity_with_hash_join(self):
        for delta in (False, True):
            db = mk(delta)
            db.run("CREATE MATERIALIZED VIEW j AS SELECT o.amt, u.name "
                   "FROM orders o JOIN users u ON o.uid = u.uid")
            self._drive(db)
            rows = sorted(db.query("SELECT * FROM j"))
            assert rows == [(100, "ann"), (200, "bo")], (delta, rows)

    def test_updates_both_sides(self):
        db = mk(True)
        db.run("CREATE MATERIALIZED VIEW j AS SELECT o.amt, u.name "
               "FROM orders o JOIN users u ON o.uid = u.uid")
        self._drive(db)
        # late user arrives: existing order joins up
        db.run("INSERT INTO users VALUES (3, 'cy')")
        ticks(db)
        assert sorted(db.query("SELECT * FROM j")) == \
            [(100, "ann"), (200, "bo"), (300, "cy")]
        # delete retracts pairs
        db.run("DELETE FROM users WHERE uid = 1")
        ticks(db)
        assert sorted(db.query("SELECT * FROM j")) == \
            [(200, "bo"), (300, "cy")]
        db.run("DELETE FROM orders WHERE oid = 11")
        ticks(db)
        assert sorted(db.query("SELECT * FROM j")) == [(300, "cy")]

    def test_same_epoch_both_sides_no_double_count(self):
        """Rows for the same key arriving on BOTH sides within one epoch
        must produce each pair exactly once (the dA json B_old vs
        A_new json dB split)."""
        db = mk(True)
        db.run("CREATE MATERIALIZED VIEW j AS SELECT o.amt, u.name "
               "FROM orders o JOIN users u ON o.uid = u.uid")
        # single epoch: both the user and their order
        db.run("INSERT INTO users VALUES (7, 'zed')")
        db.run("INSERT INTO orders VALUES (70, 7, 700)")
        ticks(db)
        assert db.query("SELECT * FROM j") == [(700, "zed")]

    def test_non_indexed_key_falls_back_to_hash_join(self):
        db = mk(True)
        # join key amt is not a pk prefix of orders -> hash join fallback
        db.run("CREATE MATERIALIZED VIEW j AS SELECT o.amt, u.name "
               "FROM orders o JOIN users u ON o.amt = u.uid")
        names = [type(e).__name__ for e in _executors(db, "j")]
        assert "HashJoinExecutor" in names, names

    def test_recovery_is_stateless(self, tmp_path):
        d = str(tmp_path / "db")
        db = Database(data_dir=d)
        db.run("SET streaming_enable_delta_join TO true")
        db.run("CREATE TABLE users (uid BIGINT PRIMARY KEY, name VARCHAR)")
        db.run("CREATE TABLE orders (oid BIGINT PRIMARY KEY, uid BIGINT,"
               " amt BIGINT)")
        db.run("CREATE INDEX orders_by_uid ON orders (uid)")
        db.run("CREATE MATERIALIZED VIEW j AS SELECT o.amt, u.name "
               "FROM orders o JOIN users u ON o.uid = u.uid")
        db.run("INSERT INTO users VALUES (1, 'ann')")
        db.run("INSERT INTO orders VALUES (10, 1, 100)")
        ticks(db)
        del db
        db2 = Database(data_dir=d)
        ticks(db2)
        assert db2.query("SELECT * FROM j") == [(100, "ann")]
        db2.run("INSERT INTO orders VALUES (11, 1, 111)")
        ticks(db2)
        assert sorted(db2.query("SELECT * FROM j")) == \
            [(100, "ann"), (111, "ann")]


class TestDropGuards:
    def test_drop_probed_index_refused_then_allowed(self):
        import pytest
        db = mk(True)
        db.run("CREATE MATERIALIZED VIEW j AS SELECT o.amt, u.name "
               "FROM orders o JOIN users u ON o.uid = u.uid")
        with pytest.raises(ValueError, match="depends on it"):
            db.run("DROP INDEX orders_by_uid")
        with pytest.raises(ValueError, match="depends on it"):
            db.run("DROP TABLE users")        # probed directly by pk
        db.run("DROP MATERIALIZED VIEW j")
        db.run("DROP INDEX orders_by_uid")
        ticks(db)                             # no livelock after the drop
        db.run("INSERT INTO users VALUES (1, 'ann')")
        ticks(db)
        assert db.query("SELECT name FROM users") == [("ann",)]


def _executors(db, name):
    """Walk the MV's executor tree."""
    obj = db.catalog.get(name)
    shared = obj.runtime.get("shared")
    root = shared.upstream if shared is not None else None
    out = []
    stack = [root] if root is not None else []
    seen = set()
    while stack:
        e = stack.pop()
        if e is None or id(e) in seen:
            continue
        seen.add(id(e))
        out.append(e)
        for attr in ("input", "left_exec", "right_exec", "port",
                     "barrier_source", "inputs"):
            v = getattr(e, attr, None)
            if isinstance(v, list):
                stack.extend(v)
            elif v is not None:
                stack.append(v)
    return out
