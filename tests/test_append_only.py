"""Append-only plan-property derivation (the reference's
`generic/agg.rs` `input.append_only()` specialization): connector sources
are insert-only, the property propagates through stateless operators, and
the device agg then keeps min/max as a single extreme column (no multiset
side state) — the `aggregate/agg_impl.rs` append-only min/max analog."""
import pytest

from risingwave_tpu.config import DeviceConfig
from risingwave_tpu.sql import Database


def _dev():
    """Per-operator device path: these tests inspect DeviceHashAggExecutor
    internals, which whole-fragment fusion replaces with one epoch program."""
    return DeviceConfig(fuse=False)


SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT, "
       "channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR) "
       "WITH (connector='nexmark', nexmark.table='bid', "
       "nexmark.max.events='2000')")


def _device_agg(db, mv):
    e = db.catalog.get(mv).runtime["shared"].upstream
    stack = [e]
    while stack:
        e = stack.pop()
        if type(e).__name__ == "DeviceHashAggExecutor":
            return e
        for attr in ("input", "port", "left_exec", "right_exec"):
            c = getattr(e, attr, None)
            if c is not None:
                stack.append(c)
    return None


def test_source_agg_uses_append_only_spec():
    db = Database(device=_dev())
    db.run(SRC)
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT auction, max(price) AS m, "
           "min(price) AS mn FROM bid GROUP BY auction")
    agg = _device_agg(db, "mv")
    assert agg is not None
    assert agg.spec.append_only and len(agg.spec.minputs) == 0


def test_append_only_survives_filter_project_window():
    db = Database(device=_dev())
    db.run(SRC)
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT window_start, max(price) "
           "AS m FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
           "WHERE price > 200 GROUP BY window_start")
    agg = _device_agg(db, "mv")
    assert agg is not None and agg.spec.append_only


def test_dml_table_agg_stays_retractable():
    """Tables accept DELETE/UPDATE, so min/max must keep the multiset."""
    db = Database(device=_dev())
    db.run("CREATE TABLE t (k INT, v BIGINT)")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT k, max(v) AS m "
           "FROM t GROUP BY k")
    agg = _device_agg(db, "mv")
    assert agg is not None
    assert not agg.spec.append_only and len(agg.spec.minputs) == 1


def test_agg_output_breaks_append_only():
    """An agg emits updates, so a second-level agg over it is retractable."""
    db = Database(device=_dev())
    db.run(SRC)
    db.run("CREATE MATERIALIZED VIEW lvl1 AS SELECT auction, count(*) AS c "
           "FROM bid GROUP BY auction")
    db.run("CREATE MATERIALIZED VIEW lvl2 AS SELECT c, count(*) AS n "
           "FROM lvl1 GROUP BY c")
    agg = _device_agg(db, "lvl2")
    assert agg is not None and not agg.spec.append_only


def test_append_only_parity_with_host_minmax():
    host, dev = Database(device="off"), Database(device=_dev())
    for db in (host, dev):
        db.run(SRC)
        db.run("CREATE MATERIALIZED VIEW mv AS SELECT auction, max(price) "
               "AS m, min(price) AS mn, count(*) AS c FROM bid "
               "GROUP BY auction")
        db.run("FLUSH")
        db.run("FLUSH")
    a = sorted(host.query("SELECT * FROM mv"))
    b = sorted(dev.query("SELECT * FROM mv"))
    assert a == b and len(a) > 10


def test_pk_source_with_conflicts_stays_retractable():
    """A user pk over a connector source can collide -> Materialize may
    emit update pairs under OVERWRITE, so downstream aggs must NOT get the
    append-only specialization (review finding: append-only spec crashed
    on the U- rows)."""
    db = Database(device=_dev())
    db.run("CREATE TABLE bid (auction BIGINT, bidder BIGINT, price BIGINT, "
           "channel VARCHAR, url VARCHAR, date_time TIMESTAMP, "
           "extra VARCHAR, PRIMARY KEY (auction)) "
           "WITH (connector='nexmark', nexmark.table='bid', "
           "nexmark.max.events='2000')")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT bidder, max(price) AS m "
           "FROM bid GROUP BY bidder")
    agg = _device_agg(db, "mv")
    if agg is not None:
        assert not agg.spec.append_only
    db.run("FLUSH")
    db.run("FLUSH")
    assert len(db.query("SELECT * FROM mv")) > 0


def test_append_only_table_rejects_delete_update():
    """APPEND ONLY makes the plan property load-bearing: DML retractions
    must be rejected at the statement level (reference forbids them)."""
    db = Database(device=_dev())
    db.run("CREATE TABLE t (k INT, v BIGINT) APPEND ONLY")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT k, max(v) AS m "
           "FROM t GROUP BY k")
    db.run("INSERT INTO t VALUES (1, 10), (1, 20)")
    assert db.query("SELECT * FROM mv") == [(1, 20)]
    with pytest.raises(ValueError, match="APPEND ONLY"):
        db.run("DELETE FROM t WHERE v = 20")
    with pytest.raises(ValueError, match="APPEND ONLY"):
        db.run("UPDATE t SET v = 0 WHERE k = 1")


def test_append_only_recovery(tmp_path):
    d = str(tmp_path)
    db = Database(data_dir=d, device=_dev())
    db.run(SRC.replace("nexmark.max.events='2000'",
                       "nexmark.max.events='1000'"))
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT auction, max(price) AS m "
           "FROM bid GROUP BY auction")
    db.run("FLUSH")
    before = sorted(db.query("SELECT * FROM mv"))
    assert len(before) > 0
    db2 = Database(data_dir=d, device=_dev())
    assert sorted(db2.query("SELECT * FROM mv")) == before
