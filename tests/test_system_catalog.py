"""System catalogs (rw_catalog analog) + EXPLAIN physical-plan rendering.
Reference: src/frontend/src/catalog/system_catalog/rw_catalog/."""
import pytest

from risingwave_tpu.sql import Database


def _db():
    db = Database(device="on")
    db.run("CREATE TABLE t (k INT, v BIGINT)")
    db.run("CREATE SOURCE bid (auction BIGINT, price BIGINT, "
           "date_time TIMESTAMP) WITH (connector='nexmark', "
           "nexmark.table='bid', nexmark.max.events='200')")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT k, sum(v) AS s "
           "FROM t GROUP BY k")
    return db


def test_rw_tables_mvs_sources():
    db = _db()
    assert db.query("SELECT name FROM rw_tables") == [("t",)]
    assert db.query("SELECT name FROM rw_materialized_views") == [("mv",)]
    assert sorted(db.query("SELECT name FROM rw_sources")) == \
        [("bid",), ("t",)]
    assert db.query("SELECT name FROM rw_sources "
                    "WHERE connector = 'nexmark'") == [("bid",)]


def test_rw_columns_and_params():
    db = _db()
    cols = db.query("SELECT name, type FROM rw_columns "
                    "WHERE relation = 't'")
    assert ("k", "int") in cols and ("v", "bigint") in cols
    db.run("ALTER SYSTEM SET checkpoint_frequency = 2")
    params = dict(db.query("SELECT * FROM rw_system_parameters"))
    assert params["checkpoint_frequency"] == "2"


def test_system_tables_compose_with_sql():
    db = _db()
    (n,) = db.query("SELECT count(*) FROM rw_columns "
                    "WHERE relation = 'mv'")[0]
    assert n == len(db.catalog.get("mv").schema)


def test_user_table_shadows_system_table():
    db = Database()
    db.run("CREATE TABLE rw_tables (x INT)")
    db.run("INSERT INTO rw_tables VALUES (42)")
    assert db.query("SELECT x FROM rw_tables") == [(42,)]


def test_explain_renders_device_plan():
    db = _db()
    plan = db.run("EXPLAIN CREATE MATERIALIZED VIEW x AS "
                  "SELECT auction, count(*) FROM bid GROUP BY auction")[0]
    assert "DeviceHashAgg" in plan and "Scan(bid)" in plan
    assert "append_only" in plan
    plan2 = db.run("EXPLAIN SELECT t.k, u.v FROM t "
                   "JOIN t AS u ON t.k = u.k")[0]
    assert "Join" in plan2 and plan2.count("Scan(t)") == 2


def test_explain_has_no_side_effects():
    db = _db()
    before = set(db.catalog.objects)
    tid = db.catalog._next_table_id
    db.run("EXPLAIN CREATE MATERIALIZED VIEW zzz AS "
           "SELECT k, count(*) FROM t GROUP BY k")
    assert set(db.catalog.objects) == before
    assert db.catalog._next_table_id == tid
    # the explained MV was never created
    with pytest.raises(KeyError):
        db.catalog.get("zzz")


def test_explain_system_table():
    db = _db()
    plan = db.run("EXPLAIN SELECT * FROM rw_tables")[0]
    assert "SysScan(rw_tables)" in plan


def test_nexmark_source_column_subset():
    """CREATE SOURCE with a column subset projects the generator chunks
    (regression: full-schema chunks crashed RowIdGen)."""
    db = _db()
    db.run("CREATE MATERIALIZED VIEW m2 AS SELECT count(*) AS c FROM bid")
    db.run("FLUSH")
    (n,) = db.query("SELECT * FROM m2")[0]
    assert n > 0
    with pytest.raises(ValueError, match="no columns"):
        db.run("CREATE SOURCE bad (nope INT) WITH (connector='nexmark', "
               "nexmark.table='bid')")


def test_rw_ddl_progress_reports_backfill():
    from risingwave_tpu.sql import Database
    db = Database()
    db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
    db.run("INSERT INTO t VALUES " +
           ", ".join(f"({i}, {i})" for i in range(3000)))
    for _ in range(3):
        db.tick()
    db.run("CREATE MATERIALIZED VIEW m AS SELECT k, v FROM t"
           " WHERE v >= 0")
    for _ in range(3):
        db.tick()
    rows = db.query("SELECT * FROM rw_ddl_progress")
    assert rows == [("m", "t", 3000, 3000, "100.0%")]
    assert db.query("SELECT count(*) FROM m") == [(3000,)]
