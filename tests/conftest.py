"""Test configuration: force an 8-virtual-device CPU platform so multi-chip
sharding paths are exercised without TPU hardware (the driver validates the
real multi-chip path separately via __graft_entry__.dryrun_multichip).

The ambient environment registers a real-TPU 'axon' backend via sitecustomize
and pins JAX_PLATFORMS=axon; env vars alone don't win over that, so we also
override the jax config directly before any backend is initialized.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
