"""Test configuration: force an 8-virtual-device CPU platform so multi-chip
sharding paths are exercised without TPU hardware (the driver validates the
real multi-chip path separately via __graft_entry__.dryrun_multichip).

The ambient environment registers a real-TPU 'axon' backend via sitecustomize
and pins JAX_PLATFORMS=axon; env vars alone don't win over that, so we also
override the jax config directly before any backend is initialized.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# Key-skew telemetry (device/skew_stats.py) extends every keyed node's
# traced step; on the CPU test platform that extra XLA compile across
# dozens of fused-path tests costs real wall the tier-1 budget doesn't
# have. Pin it OFF suite-wide; the dedicated skew tests
# (test_observability2.py) force it back on per test.
os.environ.setdefault("RW_SKEW_STATS", "0")
# Flow telemetry (traffic-per-vnode histograms) rides the same traced
# step and costs the same extra CPU-platform compile; pinned OFF
# suite-wide, forced on per test by tests/test_flow_telemetry.py.
# Production default stays ON (DeviceConfig.flow_stats).
os.environ.setdefault("RW_FLOW_STATS", "0")
# Same budget call for the agg pre-combine stage (an extra traced
# program per fused agg): pinned OFF suite-wide, forced on per test by
# the dedicated skew-defense tests (test_skew_ops.py). Production
# default stays ON (DeviceConfig.agg_precombine).
os.environ.setdefault("RW_AGG_PRECOMBINE", "0")
# And for the hot/cold state tier (a touch column in every keyed step
# plus promote/evict surgery programs): pinned OFF suite-wide, forced
# on per test by the dedicated tiering tests (test_tiering.py).
# Production default stays ON (DeviceConfig.state_tiering).
os.environ.setdefault("RW_STATE_TIERING", "0")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest

MESH_DEVICES = 8


def pytest_collection_modifyitems(config, items):
    """`mesh`-marked tests need the 8 virtual CPU devices forced above;
    if jax initialized before the XLA flag landed (or the platform
    overrode it), skip them instead of failing on make_mesh."""
    if len(jax.devices()) >= MESH_DEVICES:
        return
    skip = pytest.mark.skip(reason=f"needs {MESH_DEVICES} devices, have "
                                   f"{len(jax.devices())} (XLA_FLAGS="
                                   "--xla_force_host_platform_device_"
                                   "count did not take)")
    for item in items:
        if "mesh" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def mesh8():
    """The 8-shard 1-D device mesh the sharded fused path runs over in
    tier-1 (virtual CPU devices; `parallel/mesh.make_mesh` falls back to
    them on real-TPU hosts with fewer local chips)."""
    from risingwave_tpu.parallel.mesh import make_mesh
    return make_mesh(MESH_DEVICES)


def pytest_sessionfinish(session, exitstatus):
    """Session-end guards.

    1. AOT thread join: fused tests leave background compile-service
       workers (and queued compiles) behind; join them so no compile
       lands mid-teardown and no leaked thread flakes a later plugin
       (the threads are daemons, but a compile finishing during
       interpreter shutdown can die inside jax with a noisy traceback).
    2. CI metrics-naming lint: after the suite has exercised every code
       path that registers metrics, walk the process-global REGISTRY and
       fail the run on Prometheus-invalid metric/label names or on a
       name registered with conflicting label sets
       (utils/metrics.lint_registry).

    A collection-only run (no tests executed) has nothing to guard."""
    if getattr(session, "testscollected", 0) == 0:
        return
    try:
        from risingwave_tpu.device.compile_service import shutdown
        shutdown(join=True, timeout=60.0)
    except ImportError:
        pass
    try:
        from risingwave_tpu.device.fused import join_prewarm_threads
        join_prewarm_threads(timeout=30.0)
    except ImportError:
        pass
    from risingwave_tpu.utils.metrics import (REGISTRY, dead_telemetry,
                                              lint_registry)
    rep = session.config.pluginmanager.get_plugin("terminalreporter")

    def _say(msg, red):
        if rep is not None:
            rep.write_line(msg, red=red, yellow=not red)
        else:
            print(msg)

    problems = lint_registry(REGISTRY)
    if problems:
        for p in problems:
            _say(f"metrics lint: {p}", red=True)
        session.exitstatus = 1
    # advisory only: a labeled family no test ever touched is either dead
    # plumbing or just outside this run's subset — warn, don't fail
    for d in dead_telemetry(REGISTRY):
        _say(f"metrics lint (warn): {d}", red=False)
