"""Test configuration: force an 8-virtual-device CPU platform so multi-chip
sharding paths are exercised without TPU hardware (the driver validates the
real multi-chip path separately via __graft_entry__.dryrun_multichip)."""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import sys
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
