"""Supervision v2 chaos suite: join-fragment in-place respawn,
incremental (diff) refresh, wedged-worker reaping, failpoint-ledger
replay, and sink-boundary dedupe.

Reference analogs: `GlobalBarrierWorker::recovery` restarting ANY actor
in place (`src/meta/src/barrier/worker.rs:664`), the madsim
deterministic kill tier (`src/tests/simulation/`), and the sink
log-store exactly-once contract. PanJoin's partition-organized join
state (PAPERS.md) is what makes per-worker re-seed of a join fragment
tractable: each worker's shadow partition is an independent re-seedable
unit.

Everything here is `chaos`-marked; soak-length variants carry `slow`
too so tier-1 stays fast.
"""
import json
import os
import re
import signal
import time

import pytest

from risingwave_tpu.config import ROBUSTNESS
from risingwave_tpu.sql import Database

pytestmark = pytest.mark.chaos

AUCTION_SRC = ("CREATE SOURCE auction (id BIGINT, item_name VARCHAR,"
               " description VARCHAR, initial_bid BIGINT, reserve BIGINT,"
               " date_time TIMESTAMP, expires TIMESTAMP, seller BIGINT,"
               " category BIGINT, extra VARCHAR) WITH (connector='nexmark',"
               " nexmark.table='auction', nexmark.max.events='{n}',"
               " nexmark.chunk.size='{c}')")
PERSON_SRC = ("CREATE SOURCE person (id BIGINT, name VARCHAR,"
              " email_address VARCHAR, credit_card VARCHAR, city VARCHAR,"
              " state VARCHAR, date_time TIMESTAMP, extra VARCHAR)"
              " WITH (connector='nexmark', nexmark.table='person',"
              " nexmark.max.events='{n}', nexmark.chunk.size='{c}')")
# q3-shaped: two-source equi-join (seller = person id), remote-placed
Q3_MV = ("CREATE MATERIALIZED VIEW q3 AS SELECT p.name, p.city, p.state,"
         " a.id FROM auction a JOIN person p ON a.seller = p.id")


def find_remote(db, name, kind=None):
    obj = db.catalog.get(name)
    stack = [obj.runtime["shared"].upstream]
    while stack:
        e = stack.pop()
        r = getattr(e, "_remote", None)
        if r is not None and (kind is None or r.kind == kind):
            return r
        for attr in ("input", "left_exec", "right_exec"):
            c = getattr(e, attr, None)
            if c is not None:
                stack.append(c)
    raise AssertionError(f"no remote fragment set ({kind}) in the plan")


@pytest.fixture(autouse=True)
def _restore_robustness():
    saved = (ROBUSTNESS.respawn_backoff_s, ROBUSTNESS.spawn_backoff_s,
             ROBUSTNESS.heartbeat_timeout_s, ROBUSTNESS.wedge_kill_factor,
             ROBUSTNESS.incremental_refresh)
    ROBUSTNESS.respawn_backoff_s = 0.001
    ROBUSTNESS.spawn_backoff_s = 0.001
    yield
    (ROBUSTNESS.respawn_backoff_s, ROBUSTNESS.spawn_backoff_s,
     ROBUSTNESS.heartbeat_timeout_s, ROBUSTNESS.wedge_kill_factor,
     ROBUSTNESS.incremental_refresh) = saved


def _q3_db(n, chunk, supervise=True):
    db = Database()
    db.run(AUCTION_SRC.format(n=n, c=chunk))
    db.run(PERSON_SRC.format(n=n, c=chunk))
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement = 'process'")
    if supervise:
        db.run("SET streaming_supervision TO true")
    db.run(Q3_MV)
    return db


def _q3_oracle(n, chunk, ticks):
    db = _q3_db(n, chunk, supervise=False)
    for _ in range(ticks):
        db.tick()
    rows = sorted(db.query("SELECT * FROM q3"))
    find_remote(db, "q3").shutdown()
    return rows


# ---------------------------------------------------------------------------
# tentpole 1: join-fragment in-place respawn, bit-identical MV
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("victim", [0, 1])
def test_q3_join_worker_killed_mid_epoch_bit_identical(victim):
    """Kill one q3 join worker MID-EPOCH (right after its 7th dispatched
    left-side chunk — deterministic, seeded by construction): the
    supervisor re-seeds a successor from BOTH side shadows rolled back
    to the last delivered epoch and replays the window on both
    dispatchers. The final MV must be bit-identical to an undisturbed
    run — no RemoteWorkerDied, no DDL replay."""
    from risingwave_tpu.core.chunk import StreamChunk
    n, chunk = 20_000, 64
    ticks = n // (64 * chunk) + 4
    db = _q3_db(n, chunk)
    rfs = find_remote(db, "q3")
    assert rfs.kind == "join"
    old_pid = rfs.workers[victim].proc.pid
    vin = rfs.in_channels[0][victim]
    orig_send, seen = vin.send, [0]

    def send_and_kill(msg):
        orig_send(msg)
        if isinstance(msg, StreamChunk):
            seen[0] += 1
            if seen[0] == 7:
                rfs.workers[victim].proc.kill()
                rfs.workers[victim].proc.wait()
    vin.send = send_and_kill
    for _ in range(ticks):
        db.tick()                      # must NOT raise RemoteWorkerDied
    assert find_remote(db, "q3") is rfs, \
        "job objects must survive (in-place recovery, no DDL replay)"
    assert rfs.supervisor.respawns == 1
    assert rfs.workers[victim].proc.pid != old_pid
    assert sorted(db.query("SELECT * FROM q3")) == _q3_oracle(n, chunk,
                                                              ticks)
    rfs.shutdown()


def test_q3_join_worker_seeded_failpoint_kill_converges():
    """A seeded `fragment.drain` failpoint (coordinator-side, fires
    once) aborts one q3 join worker's result drain mid-stream: the
    supervisor treats it as a worker failure, kills + respawns the
    slot through the two-input re-seed path, and the MV converges to
    the undisturbed oracle — repeatable because the fire is seeded and
    max_fires-bounded, the chaos-ledger-friendly arming style."""
    from risingwave_tpu.utils import failpoint as fp
    n, chunk = 12_000, 64
    ticks = n // (64 * chunk) + 4
    fp.arm("fragment.drain", prob=1.0, seed=0, max_fires=1)
    try:
        db = _q3_db(n, chunk)
        rfs = find_remote(db, "q3")
        for _ in range(ticks):
            db.tick()
        assert rfs.supervisor.respawns == 1
        got = sorted(db.query("SELECT * FROM q3"))
        rfs.shutdown()
    finally:
        fp.reset()
    assert got == _q3_oracle(n, chunk, ticks)


# ---------------------------------------------------------------------------
# tentpole 3: wedged-worker reaping (SIGSTOP -> SIGKILL -> respawn)
# ---------------------------------------------------------------------------


def test_sigstop_worker_reaped_and_respawned(monkeypatch):
    """A SIGSTOP'd supervised worker stops heartbeating but never exits:
    once its heartbeat age exceeds heartbeat_timeout_s *
    wedge_kill_factor the supervisor SIGKILLs it and routes the slot
    through the normal respawn path — the job completes with exact
    results and `supervisor_wedged_reaped_total` counts the reap."""
    from risingwave_tpu.utils.metrics import REGISTRY
    # spawned workers inherit the env: their heartbeat TIMER period is
    # timeout/4, so healthy-but-quiescent siblings keep proving liveness
    # well inside the shrunken kill window
    monkeypatch.setenv("RW_HEARTBEAT_TIMEOUT_S", "1.0")
    ROBUSTNESS.heartbeat_timeout_s = 1.0
    ROBUSTNESS.wedge_kill_factor = 1.5
    db = Database()
    db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement = 'process'")
    db.run("SET streaming_supervision TO true")
    db.run("CREATE MATERIALIZED VIEW ra AS SELECT k, count(*) AS c,"
           " sum(v) AS s FROM t GROUP BY k")
    rfs = find_remote(db, "ra")
    db.run("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30), (1, 5)")
    for _ in range(4):
        db.tick()
    assert sorted(db.query("SELECT * FROM ra")) == \
        [(1, 2, 15), (2, 1, 20), (3, 1, 30)]
    victim = 0
    old_pid = rfs.workers[victim].proc.pid
    os.kill(old_pid, signal.SIGSTOP)
    # ticks stall on the stopped worker's barrier until the reaper fires
    # inside the merge idle loop; bound the wait, not the outcome
    deadline = time.monotonic() + 60
    while rfs.supervisor.reaped == 0 and time.monotonic() < deadline:
        db.tick()
    assert rfs.supervisor.reaped == 1, "wedge reaper never fired"
    assert rfs.supervisor.respawns == 1
    assert rfs.workers[victim].proc.pid != old_pid
    # the job completes: post-reap traffic aggregates exactly
    db.run("INSERT INTO t VALUES (2, 7)")
    for _ in range(4):
        db.tick()
    assert sorted(db.query("SELECT * FROM ra")) == \
        [(1, 2, 15), (2, 2, 27), (3, 1, 30)]
    assert "supervisor_wedged_reaped_total" in REGISTRY.expose()
    # the liveness surface reports the slot healthy again post-respawn
    rows = db.query("SELECT * FROM rw_worker_liveness")
    assert len(rows) == 2 and all(r[5] in ("ok", "wedged?") for r in rows)
    rfs.shutdown()


# ---------------------------------------------------------------------------
# tentpole 2: incremental refresh emits ⊆ changed groups
# ---------------------------------------------------------------------------


def _refresh_rows(mode):
    """Sum of worker_refresh_rows_total{mode=...} across the cluster
    expose (workers piggyback their registries to the coordinator)."""
    from risingwave_tpu.utils.metrics import REGISTRY
    total = 0.0
    for ln in REGISTRY.expose().splitlines():
        if ln.startswith("worker_refresh_rows_total{") \
                and f'mode="{mode}"' in ln:
            total += float(ln.rsplit(" ", 1)[1])
    return total


def test_incremental_refresh_emits_subset_of_changed_groups():
    """After a respawn, the diff refresh may only re-state groups whose
    value changed inside the crash window — not the whole owned-group
    set. 40 groups delivered, ≤3 touched in the window ⇒ the diff-mode
    refresh emits ≤ 3 rows cluster-wide and full-mode refresh stays
    unused."""
    from risingwave_tpu.core.chunk import StreamChunk
    base_diff, base_full = _refresh_rows("diff"), _refresh_rows("full")
    db = Database()
    db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement = 'process'")
    db.run("SET streaming_supervision TO true")
    db.run("CREATE MATERIALIZED VIEW ra AS SELECT k, count(*) AS c,"
           " sum(v) AS s FROM t GROUP BY k")
    rfs = find_remote(db, "ra")
    vals = ", ".join(f"({k}, {k * 10})" for k in range(40))
    db.run(f"INSERT INTO t VALUES {vals}")
    for _ in range(4):
        db.tick()
    assert len(db.query("SELECT * FROM ra")) == 40
    # crash window touches exactly 3 groups; the victim dies after its
    # next dispatched data chunk (mid-epoch, deterministic)
    victim = 0
    vin = rfs.in_channels[0][victim]
    orig_send = vin.send

    def send_and_kill(msg):
        orig_send(msg)
        if isinstance(msg, StreamChunk):
            vin.send = orig_send
            rfs.workers[victim].proc.kill()
            rfs.workers[victim].proc.wait()
    vin.send = send_and_kill
    db.run("INSERT INTO t VALUES (1, 1), (2, 2), (3, 3)")
    for _ in range(6):
        db.tick()
    assert rfs.supervisor.respawns == 1
    want = [(k, 2, k * 10 + k) if k in (1, 2, 3) else (k, 1, k * 10)
            for k in range(40)]
    assert sorted(db.query("SELECT * FROM ra")) == sorted(want)
    assert _refresh_rows("full") == base_full, \
        "v2 respawn must not fall back to the full owned-group refresh"
    emitted = _refresh_rows("diff") - base_diff
    assert emitted <= 3, \
        f"diff refresh emitted {emitted} rows for a 3-group crash window"
    rfs.shutdown()


# ---------------------------------------------------------------------------
# satellite: sink dedupe across a stateful respawn + refresh
# ---------------------------------------------------------------------------


def _replay_changelog(path):
    """Apply the sink's +/- changelog; returns the net row multiset and
    asserts multiplicities never go negative (a duplicate `+` would
    inflate one, a stale `-` would sink one below zero)."""
    state = {}
    for ln in open(path):
        rec = json.loads(ln)
        row = tuple(rec["row"][k] for k in sorted(rec["row"]))
        state[row] = state.get(row, 0) + (1 if rec["op"] == "+" else -1)
        assert state[row] >= 0, f"negative multiplicity for {row}"
        if state[row] == 0:
            del state[row]
    out = []
    for row, cnt in state.items():
        out.extend([row] * cnt)
    return sorted(out)


@pytest.mark.parametrize("incremental", [True, False])
def test_no_duplicate_rows_reach_sink_across_respawn(tmp_path,
                                                     incremental):
    """A stateful respawn + refresh must deliver ZERO duplicate rows to
    an attached sink. Incremental mode never produces them (per-epoch
    net diffs are exact); the v1 full-refresh fallback re-INSERTs every
    owned group and relies on the sink-boundary (pk, epoch) dedupe +
    the coordinator's vanished-group retraction — both paths must net
    to the exact MV, including a group fully retracted inside the crash
    window."""
    from risingwave_tpu.core.chunk import StreamChunk
    from risingwave_tpu.utils.metrics import REGISTRY
    ROBUSTNESS.incremental_refresh = incremental
    out = tmp_path / "out.jsonl"
    db = Database(data_dir=str(tmp_path / "data"))
    db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement = 'process'")
    db.run("SET streaming_supervision TO true")
    db.run("CREATE MATERIALIZED VIEW ra AS SELECT k, count(*) AS c,"
           " sum(v) AS s FROM t GROUP BY k")
    db.run(f"CREATE SINK snk FROM ra WITH (connector='fs',"
           f" fs.path='{out}')")
    rfs = find_remote(db, "ra")
    db.run("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30), (4, 40)")
    for _ in range(4):
        db.tick()
    # crash window: group 2 fully retracted, group 1 changed, group 5
    # born; BOTH workers die mid-epoch so whichever owns group 2
    # exercises the retraction path
    for w in range(2):
        vin = rfs.in_channels[0][w]
        orig = vin.send

        def send_and_kill(msg, _w=w, _orig=orig, _vin=vin):
            _orig(msg)
            if isinstance(msg, StreamChunk):
                _vin.send = _orig      # one kill per worker
                rfs.workers[_w].proc.kill()
                rfs.workers[_w].proc.wait()
        vin.send = send_and_kill
    db.run("DELETE FROM t WHERE k = 2")
    db.run("INSERT INTO t VALUES (1, 1), (5, 50)")
    for _ in range(8):
        db.tick()
    assert rfs.supervisor.respawns == 2
    want = sorted(db.query("SELECT k, count(*), sum(v)"
                           " FROM t GROUP BY k"))
    got = sorted(db.query("SELECT * FROM ra"))
    assert got == want
    # exactly-once external delivery: the changelog's net result is the
    # MV — no duplicate `+`, no stale rows, group 2 fully gone
    net = _replay_changelog(out)
    # changelog rows come back in sorted-column-name order (c, k, s)
    want_rows = sorted(tuple(str(v) for v in (r[1], r[0], r[2]))
                       for r in want)
    net = sorted(tuple(str(v) for v in r) for r in net)
    assert net == want_rows, (net, want_rows)
    assert not any(r[1] == "2" for r in net), "group 2 must be retracted"
    if not incremental:
        text = REGISTRY.expose()
        assert "supervisor_refresh_retractions_total" in text
    rfs.shutdown()


# ---------------------------------------------------------------------------
# escalation hygiene: every _escalate call site cites a registered reason
# ---------------------------------------------------------------------------


def test_escalation_reasons_registered_and_distinct():
    """Every `_escalate` call site in remote_fragments must cite a
    reason from the ESCALATION_REASONS registry (the
    supervisor_escalations_total{reason} label values), every registered
    reason must have a call site, and each reason carries its own
    documentation — a dashboard must be able to tell WHY a fragment fell
    back to full recovery from the label alone."""
    import inspect
    from risingwave_tpu.runtime import remote_fragments as rf
    src = inspect.getsource(rf)
    cited = re.findall(
        r"_escalate\((?:[^()]|\([^()]*\))*?\"([a-z_]+)\"\)", src,
        re.DOTALL)
    assert cited, "no _escalate call sites found (regex rot?)"
    assert set(cited) == set(rf.ESCALATION_REASONS), (
        sorted(set(cited) ^ set(rf.ESCALATION_REASONS)))
    # registry hygiene: distinct, documented, label-grammar-safe
    assert len(rf.ESCALATION_REASONS) == len(set(rf.ESCALATION_REASONS))
    for reason, doc in rf.ESCALATION_REASONS.items():
        assert re.fullmatch(r"[a-z][a-z0-9_]*", reason), reason
        assert doc and len(doc) > 10, reason
    # the runtime enforces the registry too
    db = _q3_db(1_000, 64)
    rfs = find_remote(db, "q3")
    with pytest.raises(AssertionError, match="unregistered"):
        rfs.supervisor._escalate("x", "not_a_real_reason")
    rfs.shutdown()


# ---------------------------------------------------------------------------
# tentpole 4: ledger record/replay reproduces the fire sequence
# ---------------------------------------------------------------------------


def test_ledger_chaos_run_replays_identical_fire_sequence(tmp_path):
    """Record a chaos run's ledger, re-arm a second run from the file
    (the RW_FAILPOINT_LEDGER contract), and assert the two runs fired
    the identical (ordinal, point, hit) sequence."""
    from risingwave_tpu.utils import failpoint as fp

    def run():
        seq = []
        for i in range(120):
            if fp.failpoint("chaos.a"):
                seq.append(("a", i))
            if i % 3 == 0 and fp.failpoint("chaos.b"):
                seq.append(("b", i))
        return seq

    fp.reset()
    fp.clear_ledger()
    fp.arm("chaos.a", prob=0.3, seed=17)
    fp.arm("chaos.b", prob=0.5, seed=4)
    seq1 = run()
    rec = fp.ledger()
    assert rec and any(p == "chaos.b" for _, p, _t, _h in rec)
    path = str(tmp_path / "chaos.ledger")
    assert fp.dump_ledger(path) == len(rec)
    # second run: armed from the file alone — no probs, no seeds
    fp.reset()
    fp.clear_ledger()
    fp.arm_from_ledger(path)
    seq2 = run()
    rep = fp.ledger()
    assert seq1 == seq2
    assert [(o, p, h) for o, p, _t, h in rec] == \
        [(o, p, h) for o, p, _t, h in rep]
    fp.reset()
    fp.clear_ledger()


def test_ledger_cross_thread_fire_sets_replay(tmp_path):
    """Two threads hammering their own points race for global ordinals,
    but each point's per-hit fire decisions are what replay pins down:
    the replayed run must fire the same (point, hit) set."""
    import threading
    from risingwave_tpu.utils import failpoint as fp

    def hammer(name, n=200):
        for _ in range(n):
            fp.failpoint(name)

    def run():
        ts = [threading.Thread(target=hammer, args=(nm,))
              for nm in ("chaos.t1", "chaos.t2")]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

    fp.reset()
    fp.clear_ledger()
    fp.arm("chaos.t1", prob=0.25, seed=5)
    fp.arm("chaos.t2", prob=0.4, seed=6)
    run()
    rec = {(p, h) for _o, p, _t, h in fp.ledger()}
    assert rec
    path = str(tmp_path / "threads.ledger")
    fp.dump_ledger(path)
    fp.reset()
    fp.clear_ledger()
    fp.arm_from_ledger(path)
    run()
    rep = {(p, h) for _o, p, _t, h in fp.ledger()}
    assert rec == rep
    fp.reset()
    fp.clear_ledger()
