"""Capacity lifecycle of fused device programs: predictive growth,
cascade-free replay accounting, high-water persistence, and the
persistent-compile-cache knob.

The growth-ladder contract (ISSUE 4): a fused MV forced to start at a
tiny capacity must (a) produce rows bit-identical to the same query with
device='off', (b) reach steady state in at most 2 growth replays with
prediction on, and (c) recover()/re-create with ZERO growth replays
thanks to persisted high-water marks.
"""
import json

import pytest

from risingwave_tpu.config import DeviceConfig, resolve_device
from risingwave_tpu.device.capacity import (bucket, predict_capacity,
                                            project)
from risingwave_tpu.sql import Database

N = 5_000
CHUNK = 32          # fused epoch = 64 * CHUNK = 2048 events

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           " nexmark.table='bid', nexmark.max.events='{n}',"
           " nexmark.chunk.size='{c}')")
Q4 = ("CREATE MATERIALIZED VIEW q4 AS SELECT auction, count(*) AS c,"
      " sum(price) AS s, max(price) AS m FROM bid GROUP BY auction")


def drive(db, n=N, chunk=CHUNK):
    for _ in range(n // (64 * chunk) + 3):
        db.tick()


def host_rows():
    db = Database(device="off")
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    drive(db)
    return sorted(db.query("SELECT * FROM q4"))


@pytest.fixture(scope="module")
def oracle():
    return host_rows()


# ---------------------------------------------------------------------------
# predictor math
# ---------------------------------------------------------------------------

def test_project_extrapolates_rate():
    assert project(0, 1_000, 100_000) == 0
    # 100 entries after 1k events, 100k horizon: at least the linear
    # extrapolation (headroom on top), never less than the observed need
    assert project(100, 1_000, 100_000) >= 100 * 100
    assert project(100, 1_000, 100_000) >= 100
    # no horizon at all: a fixed step ahead of the need
    assert project(100, 0, None) == 400
    # horizon reached (sync at drain): the need is final — size exactly
    assert project(100, 1_000, 500) == 100
    assert project(100, 1_000, 1_000) == 100


def test_predict_capacity_invariants():
    assert predict_capacity(10, 256) == 256          # fits: unchanged
    for need, cur in [(300, 256), (5_000, 1_024), (70, 64)]:
        got = predict_capacity(need, cur)
        assert got >= need and got >= cur
        assert got & (got - 1) == 0                  # pow2 bucket
    # with a horizon, the projection rides the observed rate
    got = predict_capacity(300, 256, events_seen=100, horizon=200)
    assert got == bucket(project(300, 100, 200))


def test_fused_predict_caps_respects_budget_floor():
    """The HBM budget trims headroom, never correctness: clamped targets
    stay >= the observed need and >= the current capacity."""
    db = Database(device=DeviceConfig(capacity=64))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    job = db._fused["q4"]
    job.counter = 2048
    job.hbm_budget_mb = 1          # absurdly small: everything clamps
    needs = {i: {s: c * 100 for s, c in node.cap_current().items()}
             for i, node in enumerate(job.program.nodes)}
    targets = job._predict_caps(needs)
    for i, node in enumerate(job.program.nodes):
        cur = node.cap_current()
        for s, c in cur.items():
            t = targets[i][s]
            assert t >= needs[i][s] and t >= c
            assert t & (t - 1) == 0


# ---------------------------------------------------------------------------
# the growth ladder
# ---------------------------------------------------------------------------

def test_tiny_capacity_bit_identical_and_few_replays(oracle):
    """(a) + (b): a 64-slot start must converge in <= 2 predictive growth
    replays and match the host path bit-for-bit."""
    db = Database(device=DeviceConfig(capacity=64))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    job = db._fused["q4"]
    assert job.predictive
    drive(db)
    got = sorted(db.query("SELECT * FROM q4"))
    assert got == oracle
    assert job.growth_replays >= 1, "test must exercise the ladder"
    assert job.growth_replays <= 2, (
        f"predictive sizing regressed: {job.growth_replays} growth "
        f"replays (report: {job.cap_report()})")
    rep = job.cap_report()
    assert rep["retraces"] >= 1 and rep["growths"] >= 1
    assert any(c["main"] > 64 for c in rep["nodes"].values())


def test_blind_doubling_still_correct_but_replays_more(oracle):
    """predictive_growth=false restores the old one-bucket-at-a-time
    ladder — still exact, measurably more replays than the predictor."""
    db = Database(device=DeviceConfig(capacity=64, predictive_growth=False))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    job = db._fused["q4"]
    drive(db)
    assert sorted(db.query("SELECT * FROM q4")) == oracle
    assert job.growth_replays >= 1


def test_recovery_presizes_from_high_water(tmp_path, oracle):
    """(c): a restart replays at the persisted high-water capacities —
    zero additional growth replays, same rows."""
    d = str(tmp_path / "data")
    db = Database(data_dir=d, device=DeviceConfig(capacity=64))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    drive(db)
    job = db._fused["q4"]
    assert sorted(db.query("SELECT * FROM q4")) == oracle
    replays = job.growth_replays
    assert replays >= 1
    caps = {k: dict(v) for k, v in job.cap_report()["nodes"].items()}
    db.store.close()
    del db

    db2 = Database(data_dir=d, device=DeviceConfig(capacity=64))
    job2 = db2._fused["q4"]
    # counters restored (cumulative), and the recovery replay itself
    # performed no growth — the presized states absorbed every epoch
    assert job2.growth_replays == replays
    for k, v in job2.cap_report()["nodes"].items():
        for s, c in v.items():
            assert c >= caps[k][s]
    assert sorted(db2.query("SELECT * FROM q4")) == oracle


def test_recreated_mv_presizes_from_predecessor(oracle):
    """DROP + CREATE of the same plan starts at the dropped job's
    high-water capacities (Database cap-hint registry -> try_fuse) and
    never climbs the ladder again."""
    db = Database(device=DeviceConfig(capacity=64))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    drive(db)
    job = db._fused["q4"]
    assert job.growth_replays >= 1
    caps = job.cap_hints()
    db.run("DROP MATERIALIZED VIEW q4")
    db.run(Q4)
    job2 = db._fused["q4"]
    assert job2 is not job
    for i, hint in caps.items():
        assert job2.program.nodes[i].cap_current() == hint["caps"]
    drive(db)
    assert job2.growth_replays == 0
    assert sorted(db.query("SELECT * FROM q4")) == oracle


def test_recreated_mv_different_plan_ignores_hints():
    """A DIFFERENT query under the same MV name must not inherit the old
    plan's capacities (hints match on the node's structural hash, not
    just index + type)."""
    db = Database(device=DeviceConfig(capacity=64))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    drive(db)
    assert db._fused["q4"].growth_replays >= 1       # capacities grew
    db.run("DROP MATERIALIZED VIEW q4")
    db.run("CREATE MATERIALIZED VIEW q4 AS SELECT bidder, count(*) AS c"
           " FROM bid GROUP BY bidder")
    job2 = db._fused["q4"]
    for node in job2.program.nodes:
        for cap in node.cap_current().values():
            assert cap <= 4 * 64, "stale hint presized a different plan"


# ---------------------------------------------------------------------------
# persistence schema + risectl surface
# ---------------------------------------------------------------------------

def test_job_state_rows_schema(tmp_path):
    """High-water rows live above the reserved-counter keyspace and stay
    out of key 0 (the committed event counter old stores already hold)."""
    from risingwave_tpu.device import fused as F
    d = str(tmp_path / "data")
    db = Database(data_dir=d, device=DeviceConfig(capacity=64))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    drive(db)
    job = db._fused["q4"]
    rows = {int(r[0]): int(r[1]) for r in job.job_state_table.iter_all()}
    assert rows[F._JS_COUNTER] >= N
    assert rows[F._JS_REPLAYS] == job.growth_replays
    cap_keys = [k for k in rows if k >= F._JS_CAP_BASE]
    assert cap_keys, "capacity high-water rows must persist"
    assert all(rows[k] > 0 for k in cap_keys)


def test_ctl_fused_stats(tmp_path, capsys):
    from risingwave_tpu import ctl
    d = str(tmp_path / "data")
    db = Database(data_dir=d, device=DeviceConfig(capacity=64))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    drive(db)
    replays = db._fused["q4"].growth_replays
    db.store.close()
    del db
    assert ctl.main(["fused-stats", "--data-dir", d]) == 0
    out = json.loads(capsys.readouterr().out)
    assert "q4" in out
    rep = out["q4"]
    # cumulative counters survive the reopen; recovery added none
    assert rep["growth_replays"] == replays
    assert rep["committed_events"] >= N
    assert rep["nodes"] and all(v for v in rep["nodes"].values())


def test_ctl_fused_stats_no_jobs(tmp_path, capsys):
    from risingwave_tpu import ctl
    d = str(tmp_path / "data")
    db = Database(data_dir=d)
    db.run("CREATE TABLE t (k INT)")
    db.run("FLUSH")
    db.store.close()
    assert ctl.main(["fused-stats", "--data-dir", d]) == 0
    assert "no fused device jobs" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# persistent compile cache knob
# ---------------------------------------------------------------------------

def test_compile_cache_knob(tmp_path, monkeypatch):
    import jax

    from risingwave_tpu.device import configure_compile_cache
    prev = jax.config.jax_compilation_cache_dir
    try:
        monkeypatch.delenv("RW_COMPILE_CACHE_DIR", raising=False)
        monkeypatch.delenv("RW_TPU_JAX_CACHE", raising=False)
        want = str(tmp_path / "cc")
        assert configure_compile_cache(want) is True
        assert jax.config.jax_compilation_cache_dir == want
        # the DeviceConfig knob routes through resolve_device
        want2 = str(tmp_path / "cfg")
        resolve_device(DeviceConfig(compile_cache_dir=want2))
        assert jax.config.jax_compilation_cache_dir == want2
        # RW_COMPILE_CACHE_DIR overrides any explicit directory...
        env = str(tmp_path / "env")
        monkeypatch.setenv("RW_COMPILE_CACHE_DIR", env)
        assert configure_compile_cache(want) is True
        assert jax.config.jax_compilation_cache_dir == env
        # ...and an empty override disables cleanly
        monkeypatch.setenv("RW_COMPILE_CACHE_DIR", "")
        assert configure_compile_cache(want) is False
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
