"""Overload-survival chaos suite (ISSUE 14).

The contract under test: a slow consumer or an offered-load burst must
never grow a queue without bound or wedge the barrier loop. Instead the
credit-starvation evidence (stall seconds, queue depths, sink stalls)
drives an explicit per-job ladder `normal -> throttled -> degraded ->
shedding` that throttles sources, stretches epoch cadence (zero fresh
compiles), and — only under RW_LOAD_SHED — sheds audited source windows
into `rw_shed_log`; pressure clearing walks the ladder back down with
hysteresis. With shedding off, results stay bit-identical to an
unloaded run (throttling and stretch only re-time work).

Chaos seams: `overload.slow_sink` (stalled external sink),
`overload.slow_worker` (slow exchange consumer), `overload.burst`
(10x offered load) — all failpoints, so runs are ledger-replayable.
"""
import json
import os
import struct
import time

import pytest

from risingwave_tpu.config import ROBUSTNESS, DeviceConfig
from risingwave_tpu.sql import Database
from risingwave_tpu.utils import failpoint as fp
from risingwave_tpu.utils.overload import (AdmissionBucket, LADDER,
                                           OverloadController, PRESSURE)

pytestmark = pytest.mark.chaos

_KNOBS = ("overload_ladder", "overload_window_s", "overload_high",
          "overload_low", "overload_hold_s", "overload_stretch",
          "load_shed", "select_concurrency", "sink_spool_rows",
          "exchange_credits", "fused_epoch_log_bytes")


@pytest.fixture(autouse=True)
def _fast_and_clean():
    saved = {k: getattr(ROBUSTNESS, k) for k in _KNOBS}
    fp.reset()
    PRESSURE.reset()
    yield
    fp.reset()
    PRESSURE.reset()
    for k, v in saved.items():
        setattr(ROBUSTNESS, k, v)


def _fast_ladder(hold=0.0, window=30.0):
    ROBUSTNESS.overload_hold_s = hold
    ROBUSTNESS.overload_window_s = window
    ROBUSTNESS.overload_high = 0.5
    ROBUSTNESS.overload_low = 0.1


# ---------------------------------------------------------------------------
# ladder + admission unit behavior (no dataflow)
# ---------------------------------------------------------------------------


def test_ladder_escalates_holds_and_recovers_with_hysteresis():
    """Escalation needs the pressure HELD above high for hold_s (one
    rung per hold); the dead band between low and high moves nothing;
    recovery needs a symmetric hold below low; RW_LOAD_SHED=false caps
    the ladder at `degraded`."""
    ROBUSTNESS.overload_high, ROBUSTNESS.overload_low = 0.5, 0.1
    ROBUSTNESS.overload_hold_s = 1.0
    ROBUSTNESS.overload_ladder = True
    ROBUSTNESS.load_shed = False
    c = OverloadController("j")
    t = 1000.0
    assert c.observe(0.9, t) == "normal"          # hold window opens
    assert c.observe(0.9, t + 0.5) == "normal"    # not held long enough
    assert c.observe(0.9, t + 1.1) == "throttled"
    assert c.observe(0.9, t + 1.2) == "throttled"  # fresh hold per rung
    assert c.observe(0.9, t + 2.3) == "degraded"
    assert c.observe(0.9, t + 3.5) == "degraded"   # capped: shed off
    assert c.stretch == max(1, int(ROBUSTNESS.overload_stretch))
    assert c.observe(0.3, t + 10.0) == "degraded"  # dead band: parked
    assert c.observe(0.05, t + 11.0) == "degraded"  # recovery hold opens
    assert c.observe(0.05, t + 12.1) == "throttled"
    assert c.observe(0.05, t + 13.2) == "normal"
    assert c.stretch == 1
    # with shedding enabled the top rung opens
    ROBUSTNESS.load_shed = True
    for dt, want in ((20.0, "normal"), (21.1, "throttled"),
                     (22.2, "degraded"), (23.3, "shedding")):
        assert c.observe(0.9, t + dt) == want
    # every move was recorded for rw_overload
    states = [tr[3] for tr in c.transitions]
    assert states == ["throttled", "degraded", "throttled", "normal",
                      "throttled", "degraded", "shedding"]
    rows = c.rows(now=t + 30.0)
    assert rows[0][:3] == ("j", 0, "shedding")
    assert len(rows) == 1 + len(states)


def test_admission_bucket_defers_then_sheds_only_on_shed_rung():
    b = AdmissionBucket("s", capacity=4)
    assert [b.admit() for _ in range(5)] == ["admit"] * 4 + ["defer"]
    assert b.lag == 1
    b.state, b.shed_enabled = "shedding", False
    assert b.admit() == "defer"          # shedding rung but knob OFF
    b.shed_enabled = True
    assert b.admit() == "shed"
    b.factor = 0.5
    b.epoch_refill()
    assert b.tokens == 2
    b.factor = 0.0
    b.epoch_refill()
    assert b.tokens == 1                 # floor: throttled always trickles


def test_epoch_log_spills_and_reloads(tmp_path):
    from risingwave_tpu.device.fused import _EpochLog
    log = _EpochLog(cap_bytes=8 * 16, dir_of=lambda: str(tmp_path))
    want = []
    for i in range(30):
        log.append(i * 10, 10)
        want.append((i * 10, 10))
    assert log.spilled > 0 and log.spill_total > 0
    assert len(log._mem) <= log.cap_entries
    assert os.path.exists(tmp_path / "epoch_log_spill.jsonl")
    assert log.entries() == want         # spill tier + memory, in order
    assert len(log) == 30
    log.clear()
    assert log.entries() == []
    assert not os.path.exists(tmp_path / "epoch_log_spill.jsonl")


# ---------------------------------------------------------------------------
# slow sink: stall -> escalate -> throttle -> recover (hysteresis)
# ---------------------------------------------------------------------------


def test_slow_sink_escalates_throttles_and_recovers(tmp_path):
    _fast_ladder()
    db = Database()
    db.run("CREATE TABLE t (k BIGINT, v BIGINT) WITH (connector='datagen',"
           " rows.per.poll='64')")
    path = str(tmp_path / "out.jsonl")
    db.run(f"CREATE SINK snk FROM t WITH (connector='fs',"
           f" fs.path='{path}', format='jsonl')")
    for _ in range(3):
        db.tick()
    ctrl = db._overload.controller("snk")
    assert ctrl.state == "normal"
    fp.arm("overload.slow_sink", 1.0, 0, None)
    epochs_before = db.epoch_committed
    seen = set()
    for _ in range(8):
        db.tick()
        seen.add(ctrl.state)
        time.sleep(0.01)
    # barriers kept committing THROUGH the stall
    assert db.epoch_committed > epochs_before
    # the ladder escalated (capped at degraded: RW_LOAD_SHED off)...
    assert ctrl.state == "degraded" and "throttled" in seen
    # ...sources got throttled...
    assert db._overload.buckets["t"].factor < 1.0
    assert db._overload.buckets["t"].deferred > 0
    # ...and the sink surfaced `stalled` in liveness
    live = {(r[0], r[1]): r[5] for r in db._worker_liveness_rows()}
    assert live[("snk", "sink")] == "stalled"
    # rw_overload records the walk up
    rows = db.query("SELECT * FROM rw_overload WHERE job = 'snk'")
    assert any(r[2] == "degraded" for r in rows if r[1] == 0)
    assert any(r[1] > 0 for r in rows), "transitions must be recorded"
    # fault clears: the ladder walks back down with hysteresis and the
    # backlog (parked in the durable sink log, never RSS) delivers
    fp.reset()
    deadline = time.monotonic() + 20.0
    while time.monotonic() < deadline and ctrl.state != "normal":
        db.tick()
        time.sleep(0.01)
    assert ctrl.state == "normal"
    assert not db.catalog.get("snk").runtime["sink_exec"].stalled
    assert os.path.getsize(path) > 0, "stalled backlog must deliver"
    with open(path) as f:
        assert all(json.loads(ln) for ln in f if ln.strip())


def test_real_sink_delivery_failure_isolates_instead_of_crashing(
        tmp_path, monkeypatch):
    """A REAL external delivery failure (disk full / unmounted — an
    OSError out of the sink append) takes the same isolation path as
    the chaos stall: the tick survives, the sink reads `stalled`, the
    backlog stays in the durable log, and delivery resumes when the
    external recovers."""
    from risingwave_tpu.connectors.sink import FileSink
    db = Database()
    db.run("CREATE TABLE t (k BIGINT, v BIGINT) WITH (connector='datagen',"
           " rows.per.poll='32', datagen.max.rows='512')")
    path = str(tmp_path / "out.jsonl")
    db.run(f"CREATE SINK snk FROM t WITH (connector='fs',"
           f" fs.path='{path}', format='jsonl')")
    real = FileSink.deliver
    monkeypatch.setattr(FileSink, "deliver",
                        lambda *a, **k: (_ for _ in ()).throw(
                            OSError("disk full")))
    se = db.catalog.get("snk").runtime["sink_exec"]
    for _ in range(4):
        db.tick()                        # must NOT raise
    assert se.stalled
    monkeypatch.setattr(FileSink, "deliver", real)
    for _ in range(4):
        db.tick()
    assert not se.stalled
    assert os.path.getsize(path) > 0, "backlog must deliver after the fix"


# ---------------------------------------------------------------------------
# 10x burst: bounded, bit-identical with shedding off
# ---------------------------------------------------------------------------


def _count_db(n_rows):
    db = Database()
    db.run("CREATE SOURCE s (v BIGINT) WITH (connector='datagen',"
           f" rows.per.poll='64', datagen.max.rows='{n_rows}')")
    db.run("CREATE MATERIALIZED VIEW magg AS SELECT count(*) AS n,"
           " sum(v) AS sv FROM s")
    return db


def _drain(db, deadline_s=30.0):
    """Tick until the MV stops changing (source exhausted + ladder
    drained its deferred backlog)."""
    deadline = time.monotonic() + deadline_s
    last, stable = None, 0
    while time.monotonic() < deadline:
        db.tick()
        cur = db.query("SELECT * FROM magg")
        if cur == last:
            stable += 1
            if stable >= 3 and db._overload.controllers[
                    "magg"].state == "normal":
                return cur
        else:
            stable = 0
        last = cur
        time.sleep(0.005)
    raise AssertionError(f"MV never stabilized: {last}")


def test_burst_under_pressure_is_bit_identical_with_shed_off():
    """A 10x offered-load burst while the ladder is degraded (shed OFF):
    admission defers the excess (lag grows, queues stay bounded),
    barriers keep committing, and after the pressure clears the drained
    MV is bit-identical to an unloaded run — throttling re-times work,
    it never changes it."""
    N = 65536
    want = _drain(_count_db(N), deadline_s=60.0)
    assert want[0][0] == N
    _fast_ladder()
    db = _count_db(N)
    # deterministic pressure: stall evidence pinned high for a few ticks
    # — the throttled/degraded budgets bind against the waiting data
    for _ in range(6):
        PRESSURE.note("test", 60.0)
        db.tick()
    ctrl = db._overload.controllers["magg"]
    assert ctrl.state == "degraded"
    bucket = db._overload.buckets["s"]
    assert bucket.factor < 1.0
    mid = db.query("SELECT * FROM rw_source_admission")
    assert mid[0][7] > 0, "throttled epochs must show as admission lag"
    # pressure clears (the window ages the notes out); the 10x burst
    # keeps hammering through the drain: the result must still be exact
    PRESSURE.reset()
    fp.arm("overload.burst", 1.0, 0, None)
    got = _drain(db, deadline_s=60.0)
    assert got == want
    assert bucket.deferred > 0
    assert bucket.shed_rows == 0, "no shedding with RW_LOAD_SHED=false"
    assert db.query("SELECT * FROM rw_shed_log") == []


# ---------------------------------------------------------------------------
# shedding rung: audited gaps, full accounting, recovery to normal
# ---------------------------------------------------------------------------


def test_shedding_sheds_audited_windows_and_recovers():
    """With RW_LOAD_SHED=true the top rung sheds the unadmitted windows:
    every dropped window lands in rw_shed_log, and admitted + shed
    accounts for every generated row — bounded loss with a full audit
    trail, then recovery to normal with hysteresis."""
    N = 20000
    _fast_ladder()
    ROBUSTNESS.load_shed = True
    db = _count_db(N)
    for _ in range(10):
        PRESSURE.note("test", 60.0)
        db.tick()
        time.sleep(0.002)
    ctrl = db._overload.controllers["magg"]
    assert ctrl.state == "shedding"
    bucket = db._overload.buckets["s"]
    assert bucket.shed_rows > 0, "shedding rung must actually shed"
    shed_rows = db.query("SELECT * FROM rw_shed_log")
    assert shed_rows, "every shed window must be audited"
    assert sum(r[3] for r in shed_rows) == bucket.shed_rows
    assert all(r[1] == "s" and r[4] == "admission" for r in shed_rows)
    # pressure clears: ladder recovers, remaining rows drain normally
    PRESSURE.reset()
    got = _drain(db)
    assert ctrl.state == "normal"
    # full accounting: nothing silently lost — MV rows + audited shed
    # rows cover every generated row
    assert got[0][0] + bucket.shed_rows == N
    assert got[0][0] == bucket.admitted_rows


# ---------------------------------------------------------------------------
# slow worker: credit backpressure propagates, loop closes, job exact
# ---------------------------------------------------------------------------


BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           " nexmark.table='bid', nexmark.max.events='{n}',"
           " nexmark.chunk.size='{c}')")


def test_slow_worker_backpressure_closes_the_loop(monkeypatch):
    """overload.slow_worker (armed in the WORKERS via the environment)
    makes the exchange consumers slow; with tiny credits the dispatch
    stalls, the stall seconds feed the ladder, sources throttle, and the
    job still completes exactly."""
    n = 8000
    # unloaded baseline (local placement): the exact bid-event count —
    # nexmark's max.events spans persons/auctions/bids, so the expected
    # total is a fraction of n
    base = Database()
    base.run(BID_SRC.format(n=n, c=64))
    base.run("CREATE MATERIALIZED VIEW q AS SELECT bidder, count(*) AS cnt"
             " FROM bid GROUP BY bidder")
    for _ in range(12):
        base.tick()
    expected = base.query("SELECT sum(cnt) FROM q")[0][0]
    assert expected and expected > 0
    monkeypatch.setenv("RW_FAILPOINTS", "overload.slow_worker:1")
    # short window + low threshold: the stall seconds the slow workers
    # cause each tick must dominate the window for the ladder to see
    # them promptly (production defaults are minutes-scale)
    _fast_ladder(window=2.0)
    ROBUSTNESS.overload_high = 0.15
    ROBUSTNESS.exchange_credits = 4      # queue bound 16 chunks
    db = Database()
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement TO process")
    db.run(BID_SRC.format(n=n, c=64))
    db.run("CREATE MATERIALIZED VIEW q AS SELECT bidder, count(*) AS cnt"
           " FROM bid GROUP BY bidder")
    try:
        worst = 0
        deadline = time.monotonic() + 90.0
        total = 0
        while time.monotonic() < deadline:
            for _ in range(4):
                db.tick()
                worst = max(worst, db._overload.controller("q").rung)
            rows = db.query("SELECT sum(cnt) FROM q")
            total = rows[0][0] or 0
            if total == expected:
                break
        assert total == expected, \
            "slow consumer must delay, never lose, rows"
        # the starvation was SEEN (stall notes and/or queue depth)...
        from risingwave_tpu.utils.metrics import REGISTRY
        assert "credit_stall_seconds_total" in REGISTRY.expose()
        # ...and acted on: the ladder left normal at some point
        assert worst >= 1
    finally:
        for obj in db.catalog.objects.values():
            rt = obj.runtime if isinstance(obj.runtime, dict) else None
            if rt and rt.get("shared") is not None:
                from risingwave_tpu.sql.database import _walk_executors
                for e in _walk_executors(rt["shared"].upstream):
                    r = getattr(e, "_remote", None)
                    if r is not None:
                        r.shutdown()


# ---------------------------------------------------------------------------
# fused path: cadence stretch is zero-compile; epoch log stays bounded
# ---------------------------------------------------------------------------


Q1_MV = ("CREATE MATERIALIZED VIEW q1a AS SELECT bidder, count(*) AS n,"
         " sum(price) AS dol, max(price) AS top FROM bid GROUP BY bidder")


@pytest.mark.aot
def test_fused_cadence_stretch_zero_compile_bounded_log_and_recovery(
        tmp_path):
    """Degraded-mode cadence stretch on a fused job: (1) every stretch
    transition dispatches the SAME AOT executables — zero fresh
    compiles; (2) the coordinator epoch event log stays bounded (the
    overflow spills beside epoch_profile.jsonl); (3) an in-place
    recovery mid-stretch reloads the spilled window and the final MV is
    bit-identical to an unloaded run."""
    from risingwave_tpu.device.compile_service import get_service
    N, CHUNK = 32768, 32                 # cadence 2048 -> 16 epochs
    _fast_ladder()
    ROBUSTNESS.overload_stretch = 4
    ROBUSTNESS.fused_epoch_log_bytes = 8 * 16    # cap: 8 entries
    db = Database(data_dir=str(tmp_path / "d"), checkpoint_frequency=8,
                  device=DeviceConfig(capacity=8192, aot_compile=True,
                                      compile_buckets=0))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q1_MV)
    job = db.catalog.get("q1a").runtime["fused_job"]
    svc = get_service()
    assert svc.wait_idle(180.0)
    before = svc.summary()["compiles"]
    armed = False
    for _ in range(24):
        PRESSURE.note("test", 60.0)       # sustained synthetic pressure
        db.tick()
        assert len(job._epoch_log._mem) <= job._epoch_log.cap_entries
        if not armed and job._epoch_log.spilled > 0:
            fp.arm("fused.dispatch", 1.0, 0, 1)   # fault mid-window
            armed = True
        if job.drained and job.recoveries >= 1:
            break
    fp.reset()
    assert job.cadence_stretch > 1, "ladder must have stretched cadence"
    assert armed, "the epoch log must have spilled under stretch"
    assert job.recoveries >= 1, "in-place recovery must have run"
    got = db.query("SELECT * FROM q1a")
    assert svc.wait_idle(180.0)
    assert svc.summary()["compiles"] == before, \
        "cadence stretch + recovery must be zero-fresh-compile"
    # bit-identity vs an unloaded run of the same stream
    PRESSURE.reset()
    ROBUSTNESS.overload_ladder = False
    db2 = Database(device=DeviceConfig(capacity=8192))
    db2.run(BID_SRC.format(n=N, c=CHUNK))
    db2.run(Q1_MV)
    for _ in range(N // 2048 + 3):
        db2.tick()
    assert got == db2.query("SELECT * FROM q1a")


def test_mv_rows_now_recovers_from_select_path_device_fault():
    """The PR 12 residual: a device fault during a SELECT's sync heals
    through the same in-place recovery as the barrier path and the
    query retries — no XlaRuntimeError to the client."""
    N, CHUNK = 4096, 32
    db = Database(checkpoint_frequency=1000,   # syncs only at SELECTs
                  device=DeviceConfig(capacity=2048))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q1_MV)
    job = db.catalog.get("q1a").runtime["fused_job"]
    for _ in range(N // 2048 + 2):
        db.tick()
    fp.arm("fused.device_sync", 1.0, 0, 1)
    got = db.query("SELECT * FROM q1a")      # fault fires inside here
    fp.reset()
    assert job.recoveries >= 1
    db2 = Database(device=DeviceConfig(capacity=2048))
    db2.run(BID_SRC.format(n=N, c=CHUNK))
    db2.run(Q1_MV)
    for _ in range(N // 2048 + 2):
        db2.tick()
    assert sorted(got) == sorted(db2.query("SELECT * FROM q1a"))


# ---------------------------------------------------------------------------
# front door: SELECT admission + clear fused-requeue errors
# ---------------------------------------------------------------------------


def test_pgwire_select_admission_rejects_with_sqlstate_53000():
    from test_pgwire import MiniClient
    from risingwave_tpu.pgwire import PgServer
    db = Database()
    db.run("CREATE TABLE t (v BIGINT)")
    srv = PgServer(db).start()
    try:
        c = MiniClient(srv.host, srv.port)
        c.startup()
        # one in-flight SELECT already holds the only slot: the next
        # front-door SELECT must be refused with SQLSTATE 53000
        ROBUSTNESS.select_concurrency = 1
        assert db.select_gate.enter() is True
        try:
            msgs = c.query("SELECT * FROM t")
        finally:
            db.select_gate.leave()
        errs = [b for t_, b in msgs if t_ == b"E"]
        assert errs and b"C53000\x00" in errs[0], errs
        # the connection stays usable once the slot frees
        msgs = c.query("SELECT 1")
        assert c.rows(msgs) == [("1",)]
        # the repo knob convention: <= 0 DISABLES the gate entirely
        ROBUSTNESS.select_concurrency = 0
        msgs = c.query("SELECT 1")
        assert c.rows(msgs) == [("1",)]
        from risingwave_tpu.utils.metrics import REGISTRY
        assert "select_admission_rejected_total" in REGISTRY.expose()
    finally:
        srv.stop()


def test_dlq_requeue_against_fused_job_fails_with_clear_error():
    """`risectl dlq --requeue` against a fused job used to fall through
    to 'requeued 0 rows'; now it names the reason and the way out."""
    db = Database(device=DeviceConfig(capacity=512))
    db.run(BID_SRC.format(n=2048, c=32))
    db.run(Q1_MV)
    assert db.catalog.get("q1a").runtime["fused_job"] is not None
    with pytest.raises(ValueError, match="FUSED device job"):
        db.dlq_requeue("q1a")
    with pytest.raises(ValueError, match="no such job"):
        db.dlq_requeue("nope")
    with pytest.raises(ValueError, match="no live remote worker set"):
        db.run("CREATE TABLE h (k BIGINT)")   # local-placement table
        db.dlq_requeue("h")
