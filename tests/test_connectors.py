"""Connector framework (VERDICT #9): SplitEnumerator/SplitReader/Parser
generalized beyond Nexmark, a filesystem source with offset-in-state
recovery, and an exactly-once file sink with an epoch manifest.
Reference: src/connector/src/source/base.rs:77,474, sink/mod.rs:602."""
import json
import os

import pytest

from risingwave_tpu.sql import Database


def _write_jsonl(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_fs_source_json_to_mv(tmp_path):
    src = tmp_path / "in"
    src.mkdir()
    _write_jsonl(src / "a.jsonl", [
        {"k": 1, "v": 10, "s": "x"},
        {"k": 2, "v": 20, "s": "y"},
        {"k": 1, "v": 5, "s": None},
    ])
    _write_jsonl(src / "b.jsonl", [
        {"k": 2, "v": 7},                       # missing field -> NULL
    ])
    db = Database()
    db.run(f"CREATE SOURCE s (k INT, v BIGINT, s VARCHAR) WITH ("
           f"connector='fs', fs.path='{src}', format='json')")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT k, count(*) AS c, "
           "sum(v) AS sv FROM s GROUP BY k")
    db.run("FLUSH")
    db.run("FLUSH")
    assert sorted(db.query("SELECT * FROM mv")) == [(1, 2, 15), (2, 2, 27)]


def test_fs_source_csv_and_late_files(tmp_path):
    src = tmp_path / "in"
    src.mkdir()
    (src / "1.csv").write_text("1,10\n2,20\n")
    db = Database()
    db.run(f"CREATE SOURCE s (k INT, v BIGINT) WITH (connector='fs', "
           f"fs.path='{src}', fs.pattern='*.csv', format='csv')")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT sum(v) AS s FROM s")
    db.run("FLUSH")
    db.run("FLUSH")
    assert db.query("SELECT * FROM mv") == [(30,)]
    # a file that appears later is a NEW split (re-enumeration contract)
    (src / "2.csv").write_text("3,5\n")
    db.run("FLUSH")
    db.run("FLUSH")
    assert db.query("SELECT * FROM mv") == [(35,)]


def test_fs_source_partial_trailing_line(tmp_path):
    """A writer mid-append must not produce a torn record: the reader
    stops at the last complete line and resumes when the newline lands."""
    src = tmp_path / "in"
    src.mkdir()
    with open(src / "a.jsonl", "w") as f:
        f.write('{"k": 1}\n{"k": 2')      # torn second record
    db = Database()
    db.run(f"CREATE SOURCE s (k INT) WITH (connector='fs', "
           f"fs.path='{src}')")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT count(*) AS c FROM s")
    db.run("FLUSH")
    db.run("FLUSH")
    assert db.query("SELECT * FROM mv") == [(1,)]
    with open(src / "a.jsonl", "a") as f:
        f.write("}\n")
    db.run("FLUSH")
    db.run("FLUSH")
    assert db.query("SELECT * FROM mv") == [(2,)]


def test_fs_source_offset_recovery(tmp_path):
    """Split offsets persist in the split state table: restart resumes
    where the checkpoint left off — new rows appended after the crash are
    picked up, already-read rows are not re-read."""
    src = tmp_path / "in"
    src.mkdir()
    data = tmp_path / "data"
    _write_jsonl(src / "a.jsonl", [{"k": i} for i in range(5)])
    db = Database(data_dir=str(data))
    db.run(f"CREATE SOURCE s (k INT) WITH (connector='fs', "
           f"fs.path='{src}')")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT count(*) AS c FROM s")
    db.run("FLUSH")
    db.run("FLUSH")
    assert db.query("SELECT * FROM mv") == [(5,)]

    with open(src / "a.jsonl", "a") as f:          # rows during downtime
        f.write('{"k": 100}\n{"k": 101}\n')
    db2 = Database(data_dir=str(data))             # restart
    db2.run("FLUSH")
    db2.run("FLUSH")
    assert db2.query("SELECT * FROM mv") == [(7,)]


def test_file_sink_exactly_once(tmp_path):
    out = tmp_path / "out.jsonl"
    db = Database()
    db.run("CREATE TABLE t (k INT, v BIGINT)")
    db.run(f"CREATE SINK snk FROM t WITH (connector='fs', "
           f"fs.path='{out}', format='jsonl')")
    db.run("INSERT INTO t VALUES (1, 10), (2, 20)")
    db.run("DELETE FROM t WHERE k = 1")
    lines = [json.loads(ln) for ln in open(out)]
    ops = [(ln["op"], ln["row"]["k"]) for ln in lines]
    assert ops == [("+", 1), ("+", 2), ("-", 1)]
    # manifest matches the file exactly
    m = json.load(open(str(out) + ".manifest"))
    assert m["bytes"] == os.path.getsize(out)


def test_file_sink_truncates_uncommitted_tail(tmp_path):
    """Crash between append and manifest commit: recovery must truncate
    the unmanifested tail (no duplicates, no torn rows)."""
    out = tmp_path / "out.jsonl"
    db = Database()
    db.run("CREATE TABLE t (k INT)")
    db.run(f"CREATE SINK snk FROM t WITH (connector='fs', "
           f"fs.path='{out}')")
    db.run("INSERT INTO t VALUES (1)")
    committed = open(out).read()
    with open(out, "a") as f:                 # simulate torn post-manifest
        f.write('{"op": "+", "row": {"k": 999}}\n')
    from risingwave_tpu.connectors.sink import FileSink
    from risingwave_tpu.core.schema import Schema
    from risingwave_tpu.core import dtypes as T
    FileSink(str(out), Schema.of(("k", T.INT32)))   # recovery ctor
    assert open(out).read() == committed


def test_file_sink_restart_no_duplicates(tmp_path):
    """Kill/restart with DDL replay: replayed epochs <= the manifest's
    committed epoch are skipped, so the sink file has each row once."""
    out = tmp_path / "out.jsonl"
    data = tmp_path / "data"
    db = Database(data_dir=str(data))
    db.run("CREATE TABLE t (k INT)")
    db.run(f"CREATE SINK snk FROM t WITH (connector='fs', "
           f"fs.path='{out}')")
    db.run("INSERT INTO t VALUES (1), (2)")

    db2 = Database(data_dir=str(data))             # restart, replay DDL
    db2.run("INSERT INTO t VALUES (3)")
    ks = [json.loads(ln)["row"]["k"] for ln in open(out)]
    assert sorted(ks) == [1, 2, 3]


def test_json_parser_skips_non_object_records(tmp_path):
    """Valid-JSON-but-not-an-object lines (arrays, numbers) are counted
    as errors, not crashes (review finding)."""
    src = tmp_path / "in"
    src.mkdir()
    (src / "a.jsonl").write_text('{"k": 1}\n[1, 2]\n42\n"str"\n{"k": 2}\n')
    db = Database()
    db.run(f"CREATE SOURCE s (k INT) WITH (connector='fs', "
           f"fs.path='{src}')")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT count(*) AS c FROM s")
    db.run("FLUSH")
    db.run("FLUSH")
    assert db.query("SELECT * FROM mv") == [(2,)]


def test_csv_quoting_roundtrip(tmp_path):
    """Sink CSV quotes delimiter-bearing values (RFC-4180) and the parser
    reads them back intact (review finding: no quoting = column shift)."""
    out = tmp_path / "out.csv"
    db = Database()
    db.run("CREATE TABLE t (k INT, s VARCHAR)")
    db.run(f"CREATE SINK snk FROM t WITH (connector='fs', "
           f"fs.path='{out}', format='csv')")
    db.run("INSERT INTO t VALUES (1, 'a,b'), (2, 'he said \"hi\"')")
    # read back through the CSV parser: no column shift, quotes intact
    src = tmp_path / "in"
    src.mkdir()
    os.rename(out, src / "rows.csv")
    db2 = Database()
    db2.run(f"CREATE SOURCE s (op VARCHAR, k INT, s VARCHAR) WITH ("
            f"connector='fs', fs.path='{src}', format='csv')")
    db2.run("CREATE MATERIALIZED VIEW mv AS SELECT k, s FROM s")
    db2.run("FLUSH")
    db2.run("FLUSH")
    assert sorted(db2.query("SELECT * FROM mv")) == \
        [(1, "a,b"), (2, 'he said "hi"')]


def test_source_file_shrink_fails_loudly(tmp_path):
    """A source file rotated/truncated below the committed offset is an
    error, not a silent stall (review finding)."""
    from risingwave_tpu.connectors.filesystem import LineFileReader
    from risingwave_tpu.connectors.base import SourceSplit
    p = tmp_path / "a.jsonl"
    p.write_text('{"k": 1}\n{"k": 2}\n')
    r = LineFileReader()
    recs, off = r.read(SourceSplit("a", str(p)), None, 10)
    assert len(recs) == 2
    p.write_text('{"k": 9}\n')                 # rotated shorter
    with pytest.raises(IOError, match="shrank"):
        r.read(SourceSplit("a", str(p)), off, 10)


def test_sink_log_recovers_undelivered_epoch(tmp_path, monkeypatch):
    """Crash window between checkpoint and external delivery (review
    finding): the epoch's rows are durable in the sink LOG table, so
    restart delivers them — exactly once, no loss, no duplicates."""
    out = tmp_path / "out.jsonl"
    data = tmp_path / "data"
    db = Database(data_dir=str(data))
    db.run("CREATE TABLE t (k INT)")
    db.run(f"CREATE SINK snk FROM t WITH (connector='fs', "
           f"fs.path='{out}')")
    from risingwave_tpu.connectors.sink import FileSink
    # simulate dying before any external delivery happens
    monkeypatch.setattr(FileSink, "deliver", lambda self, e, p: None)
    db.run("INSERT INTO t VALUES (1), (2)")
    assert not out.exists()
    monkeypatch.undo()

    db2 = Database(data_dir=str(data))             # restart
    db2.run("FLUSH")
    ks = [json.loads(ln)["row"]["k"] for ln in open(out)]
    assert sorted(ks) == [1, 2]
    db2.run("INSERT INTO t VALUES (3)")
    ks = [json.loads(ln)["row"]["k"] for ln in open(out)]
    assert sorted(ks) == [1, 2, 3]


def test_sink_refuses_foreign_file(tmp_path):
    """A pre-existing file without a sink manifest is someone else's data
    — creating the sink must refuse, not truncate (review finding)."""
    out = tmp_path / "precious.jsonl"
    out.write_text("do not delete\n")
    db = Database()
    db.run("CREATE TABLE t (k INT)")
    with pytest.raises(FileExistsError, match="refusing"):
        db.run(f"CREATE SINK snk FROM t WITH (connector='fs', "
               f"fs.path='{out}')")
    assert out.read_text() == "do not delete\n"


def test_parser_bad_decimal_counted_not_crash(tmp_path):
    src = tmp_path / "in"
    src.mkdir()
    (src / "a.jsonl").write_text('{"d": "abc"}\n{"d": "1.5"}\n')
    db = Database()
    db.run(f"CREATE SOURCE s (d DECIMAL) WITH (connector='fs', "
           f"fs.path='{src}')")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT count(*) AS c FROM s")
    db.run("FLUSH")
    db.run("FLUSH")
    assert db.query("SELECT * FROM mv") == [(1,)]


def test_reader_preserves_field_whitespace(tmp_path):
    src = tmp_path / "in"
    src.mkdir()
    (src / "a.csv").write_text(" x,1\n")
    db = Database()
    db.run(f"CREATE SOURCE s (s VARCHAR, k INT) WITH (connector='fs', "
           f"fs.path='{src}', format='csv')")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT s, k FROM s")
    db.run("FLUSH")
    db.run("FLUSH")
    assert db.query("SELECT * FROM mv") == [(" x", 1)]


def test_append_only_source_sink_writes_bare_rows(tmp_path):
    src = tmp_path / "in"
    src.mkdir()
    out = tmp_path / "out.jsonl"
    _write_jsonl(src / "a.jsonl", [{"k": 1}, {"k": 2}])
    db = Database()
    db.run(f"CREATE SOURCE s (k INT) WITH (connector='fs', "
           f"fs.path='{src}')")
    db.run(f"CREATE SINK snk FROM s WITH (connector='fs', "
           f"fs.path='{out}')")
    db.run("FLUSH")
    db.run("FLUSH")
    rows = [json.loads(ln) for ln in open(out)]
    assert sorted(r["k"] for r in rows) == [1, 2]
    assert all("op" not in r for r in rows)    # append-only: bare rows
