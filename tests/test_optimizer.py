"""Logical optimizer rules (SURVEY L9 gap: zero rules before this).
Reference: src/frontend/src/optimizer/rule/{const_eval,predicate_push_down}.
Each rule is checked two ways: the rewrite fires (EXPLAIN / applied_rules)
AND results stay identical to the unoptimized semantics."""
import numpy as np
import pytest

from risingwave_tpu.sql import Database
from risingwave_tpu.sql import ast as A
from risingwave_tpu.sql.optimizer import optimize
from risingwave_tpu.sql.parser import parse_sql


def _opt(sql):
    (q,) = parse_sql(sql)
    optimize(q)
    return q


def test_constant_folding():
    q = _opt("SELECT k FROM t WHERE v > 1 + 2 * 3")
    assert isinstance(q.where, A.BinOp)
    assert isinstance(q.where.right, A.Lit) and q.where.right.value == 7
    assert any(r.startswith("const_fold") for r in q.applied_rules)


def test_where_true_dropped_and_bool_short_circuit():
    q = _opt("SELECT k FROM t WHERE TRUE")
    assert q.where is None
    q = _opt("SELECT k FROM t WHERE TRUE AND v > 1")
    assert isinstance(q.where, A.BinOp) and q.where.op == ">"
    q = _opt("SELECT k FROM t WHERE v > 1 OR TRUE")
    assert q.where is None


def test_predicate_pushdown_below_agg():
    """A group-key predicate over an agg subquery moves below the agg —
    filtering before grouping shrinks operator state."""
    q = _opt("SELECT s.k, s.c FROM (SELECT k, count(*) AS c FROM t "
             "GROUP BY k) AS s WHERE s.k > 5")
    assert q.where is None
    inner = q.from_.query
    assert inner.where is not None
    assert "push_predicate_below_agg" in q.applied_rules


def test_predicate_on_agg_output_becomes_having():
    q = _opt("SELECT s.k, s.c FROM (SELECT k, count(*) AS c FROM t "
             "GROUP BY k) AS s WHERE s.c > 2")
    assert q.where is None
    assert q.from_.query.having is not None
    assert "push_predicate_to_having" in q.applied_rules


def test_no_pushdown_into_nullable_outer_side_or_limit():
    q = _opt("SELECT a.k FROM t AS a LEFT JOIN (SELECT k FROM u) AS b "
             "ON a.k = b.k WHERE b.k > 5")
    assert q.where is not None        # b is the nullable side: stays put
    q = _opt("SELECT s.k FROM (SELECT k FROM t ORDER BY k LIMIT 3) AS s "
             "WHERE s.k > 5")
    assert q.where is not None        # below LIMIT would change results


def test_unary_and_not_survive_folding():
    """Regression (review finding): UnaryOp's field is `operand`."""
    q = _opt("SELECT k FROM t WHERE v > -1")
    assert q.where is not None
    q = _opt("SELECT k FROM t WHERE NOT (v > 1 + 1)")
    assert isinstance(q.where, A.UnaryOp)
    assert isinstance(q.where.operand.right, A.Lit)
    assert q.where.operand.right.value == 2
    q = _opt("SELECT k FROM t WHERE NOT FALSE")
    assert q.where is None


def test_case_expr_blocks_cross_table_pushdown():
    """Regression (review finding): columns inside CASE branches must be
    visible to the pushdown safety check."""
    q = _opt("SELECT s.k FROM (SELECT k, count(*) AS c FROM t GROUP BY k) "
             "AS s JOIN u AS b ON s.k = b.k "
             "WHERE s.k = CASE WHEN b.v > 0 THEN 1 ELSE 2 END")
    assert q.where is not None                    # references both tables
    assert q.from_.left.query.where is None       # nothing pushed


def test_window_function_output_blocks_pushdown():
    """Regression (review finding): predicates over OVER() outputs must
    not move below the window evaluation."""
    q = _opt("SELECT s.k FROM (SELECT k, row_number() OVER "
             "(PARTITION BY k ORDER BY v) AS rn FROM t) AS s "
             "WHERE s.rn = 1")
    assert q.where is not None
    assert q.from_.query.where is None


def test_explain_shows_rewrites():
    db = Database()
    db.run("CREATE TABLE t (k INT, v BIGINT)")
    plan = db.run("EXPLAIN SELECT s.k FROM (SELECT k, sum(v) AS s2 FROM t "
                  "GROUP BY k) AS s WHERE s.k > 1 + 1")[0]
    assert "-- rewrites:" in plan and "const_fold" in plan


def test_optimized_results_match_unoptimized():
    """End-to-end: randomized data, queries exercising every rule, results
    must equal a by-hand unoptimized computation."""
    rng = np.random.default_rng(13)
    db = Database()
    db.run("CREATE TABLE t (k INT, v BIGINT)")
    rows = ", ".join(f"({int(rng.integers(0, 8))}, "
                     f"{int(rng.integers(-40, 40))})" for _ in range(150))
    db.run(f"INSERT INTO t VALUES {rows}")
    got = sorted(db.query(
        "SELECT s.k, s.c FROM (SELECT k, count(*) AS c FROM t GROUP BY k) "
        "AS s WHERE s.k > 2 AND s.c > 1 + 1"))
    want = sorted(r for r in db.query(
        "SELECT k, count(*) FROM t GROUP BY k") if r[0] > 2 and r[1] > 2)
    assert got == want and len(got) > 0

    # pushdown also applies to streaming MVs (same planner entry)
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT s.k, s.c FROM "
           "(SELECT k, count(*) AS c FROM t GROUP BY k) AS s "
           "WHERE s.k > 2 AND s.c > 2")
    db.run("INSERT INTO t VALUES (3, 1), (3, 2), (3, 3)")
    got_mv = sorted(db.query("SELECT * FROM mv"))
    want2 = sorted(r for r in db.query(
        "SELECT k, count(*) FROM t GROUP BY k") if r[0] > 2 and r[1] > 2)
    assert got_mv == want2


class TestJoinReorder:
    """Cost-based inner-join chain reordering (rule framework +
    RuleContext.rows — `src/frontend/src/optimizer/` stage/rule analog)."""

    def _db(self):
        from risingwave_tpu.sql import Database
        db = Database()
        db.run("CREATE TABLE big (k BIGINT, v BIGINT)")
        db.run("CREATE TABLE mid (k BIGINT, w BIGINT)")
        db.run("CREATE TABLE small (k BIGINT, x BIGINT)")
        db.run("INSERT INTO big VALUES " +
               ", ".join(f"({i % 7}, {i})" for i in range(200)))
        db.run("INSERT INTO mid VALUES " +
               ", ".join(f"({i}, {i})" for i in range(20)))
        db.run("INSERT INTO small VALUES (1, 100), (2, 200)")
        for _ in range(3):
            db.tick()
        return db

    def test_reorders_smallest_first_and_stays_correct(self):
        db = self._db()
        plan = db.run("EXPLAIN CREATE MATERIALIZED VIEW j AS "
                      "SELECT big.v, mid.w, small.x FROM big "
                      "JOIN mid ON big.k = mid.k "
                      "JOIN small ON mid.k = small.k")[0]
        assert "join_reorder" in str(plan), plan
        db.run("CREATE MATERIALIZED VIEW j AS "
               "SELECT big.v, mid.w, small.x FROM big "
               "JOIN mid ON big.k = mid.k "
               "JOIN small ON mid.k = small.k")
        for _ in range(3):
            db.tick()
        got = sorted(db.query("SELECT * FROM j"))
        # oracle: rows where big.k == mid.k == small.k (k in {1, 2})
        want = sorted((v, k, k * 100) for k in (1, 2)
                      for v in range(200) if v % 7 == k)
        assert got == want and len(got) > 0

    def test_no_reorder_without_connecting_predicate(self):
        db = self._db()
        # small connects only to mid; a reorder must never create a
        # cross product between small and big
        db.run("CREATE MATERIALIZED VIEW j2 AS "
               "SELECT big.v, small.x FROM big "
               "JOIN mid ON big.k = mid.k "
               "JOIN small ON mid.w = small.x")
        for _ in range(3):
            db.tick()
        # oracle: big.k == mid.k AND mid.w == small.x
        want = sorted((v, x) for v in range(200) for mk in [v % 7]
                      if mk < 20 for x in (100, 200) if mk == x)
        got = sorted(db.query("SELECT * FROM j2"))
        assert got == want, (len(got), len(want))

    def test_outer_join_chains_keep_shape(self):
        db = self._db()
        plan = db.run("EXPLAIN CREATE MATERIALIZED VIEW j3 AS "
                      "SELECT big.v FROM big "
                      "LEFT JOIN mid ON big.k = mid.k "
                      "LEFT JOIN small ON big.k = small.k")[0]
        assert "join_reorder" not in str(plan)

    def test_star_select_keeps_join_order(self):
        db = self._db()
        plan = db.run("EXPLAIN CREATE MATERIALIZED VIEW js AS "
                      "SELECT * FROM big "
                      "JOIN mid ON big.k = mid.k "
                      "JOIN small ON mid.k = small.k")[0]
        assert "join_reorder" not in str(plan)

    def test_residual_only_link_does_not_count_as_connectivity(self):
        """A single-table or non-equi conjunct must not be treated as a
        join link (the rebuilt join would have no equi-condition and the
        planner would reject a previously-valid query)."""
        from risingwave_tpu.sql import Database
        db = Database()
        db.run("CREATE TABLE a (k BIGINT, v BIGINT)")
        db.run("CREATE TABLE b (k BIGINT, j BIGINT)")
        db.run("CREATE TABLE c (j BIGINT, x BIGINT)")
        db.run("INSERT INTO a VALUES (1, 1), (2, 2)")
        db.run("INSERT INTO b VALUES " +
               ", ".join(f"({i % 3}, {i % 4})" for i in range(50)))
        db.run("INSERT INTO c VALUES " +
               ", ".join(f"({i % 4}, {i})" for i in range(10)))
        for _ in range(3):
            db.tick()
        # sizes a=2 < c=10 < b=50: naive greedy would try a ⋈ c via the
        # single-table conjunct c.x > 5 — must plan fine instead
        db.run("CREATE MATERIALIZED VIEW jr AS SELECT a.v, c.x FROM a "
               "JOIN b ON a.k = b.k "
               "JOIN c ON b.j = c.j AND c.x > 5")
        for _ in range(3):
            db.tick()
        want = sorted((a_v, c_x)
                      for a_k, a_v in ((1, 1), (2, 2))
                      for i in range(50) if i % 3 == a_k
                      for c_j, c_x in ((j % 4, j) for j in range(10))
                      if i % 4 == c_j and c_x > 5)
        assert sorted(db.query("SELECT * FROM jr")) == want
