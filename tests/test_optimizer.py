"""Logical optimizer rules (SURVEY L9 gap: zero rules before this).
Reference: src/frontend/src/optimizer/rule/{const_eval,predicate_push_down}.
Each rule is checked two ways: the rewrite fires (EXPLAIN / applied_rules)
AND results stay identical to the unoptimized semantics."""
import numpy as np
import pytest

from risingwave_tpu.sql import Database
from risingwave_tpu.sql import ast as A
from risingwave_tpu.sql.optimizer import optimize
from risingwave_tpu.sql.parser import parse_sql


def _opt(sql):
    (q,) = parse_sql(sql)
    optimize(q)
    return q


def test_constant_folding():
    q = _opt("SELECT k FROM t WHERE v > 1 + 2 * 3")
    assert isinstance(q.where, A.BinOp)
    assert isinstance(q.where.right, A.Lit) and q.where.right.value == 7
    assert any(r.startswith("const_fold") for r in q.applied_rules)


def test_where_true_dropped_and_bool_short_circuit():
    q = _opt("SELECT k FROM t WHERE TRUE")
    assert q.where is None
    q = _opt("SELECT k FROM t WHERE TRUE AND v > 1")
    assert isinstance(q.where, A.BinOp) and q.where.op == ">"
    q = _opt("SELECT k FROM t WHERE v > 1 OR TRUE")
    assert q.where is None


def test_predicate_pushdown_below_agg():
    """A group-key predicate over an agg subquery moves below the agg —
    filtering before grouping shrinks operator state."""
    q = _opt("SELECT s.k, s.c FROM (SELECT k, count(*) AS c FROM t "
             "GROUP BY k) AS s WHERE s.k > 5")
    assert q.where is None
    inner = q.from_.query
    assert inner.where is not None
    assert "push_predicate_below_agg" in q.applied_rules


def test_predicate_on_agg_output_becomes_having():
    q = _opt("SELECT s.k, s.c FROM (SELECT k, count(*) AS c FROM t "
             "GROUP BY k) AS s WHERE s.c > 2")
    assert q.where is None
    assert q.from_.query.having is not None
    assert "push_predicate_to_having" in q.applied_rules


def test_no_pushdown_into_nullable_outer_side_or_limit():
    q = _opt("SELECT a.k FROM t AS a LEFT JOIN (SELECT k FROM u) AS b "
             "ON a.k = b.k WHERE b.k > 5")
    assert q.where is not None        # b is the nullable side: stays put
    q = _opt("SELECT s.k FROM (SELECT k FROM t ORDER BY k LIMIT 3) AS s "
             "WHERE s.k > 5")
    assert q.where is not None        # below LIMIT would change results


def test_unary_and_not_survive_folding():
    """Regression (review finding): UnaryOp's field is `operand`."""
    q = _opt("SELECT k FROM t WHERE v > -1")
    assert q.where is not None
    q = _opt("SELECT k FROM t WHERE NOT (v > 1 + 1)")
    assert isinstance(q.where, A.UnaryOp)
    assert isinstance(q.where.operand.right, A.Lit)
    assert q.where.operand.right.value == 2
    q = _opt("SELECT k FROM t WHERE NOT FALSE")
    assert q.where is None


def test_case_expr_blocks_cross_table_pushdown():
    """Regression (review finding): columns inside CASE branches must be
    visible to the pushdown safety check."""
    q = _opt("SELECT s.k FROM (SELECT k, count(*) AS c FROM t GROUP BY k) "
             "AS s JOIN u AS b ON s.k = b.k "
             "WHERE s.k = CASE WHEN b.v > 0 THEN 1 ELSE 2 END")
    assert q.where is not None                    # references both tables
    assert q.from_.left.query.where is None       # nothing pushed


def test_window_function_output_blocks_pushdown():
    """Regression (review finding): predicates over OVER() outputs must
    not move below the window evaluation."""
    q = _opt("SELECT s.k FROM (SELECT k, row_number() OVER "
             "(PARTITION BY k ORDER BY v) AS rn FROM t) AS s "
             "WHERE s.rn = 1")
    assert q.where is not None
    assert q.from_.query.where is None


def test_explain_shows_rewrites():
    db = Database()
    db.run("CREATE TABLE t (k INT, v BIGINT)")
    plan = db.run("EXPLAIN SELECT s.k FROM (SELECT k, sum(v) AS s2 FROM t "
                  "GROUP BY k) AS s WHERE s.k > 1 + 1")[0]
    assert "-- rewrites:" in plan and "const_fold" in plan


def test_optimized_results_match_unoptimized():
    """End-to-end: randomized data, queries exercising every rule, results
    must equal a by-hand unoptimized computation."""
    rng = np.random.default_rng(13)
    db = Database()
    db.run("CREATE TABLE t (k INT, v BIGINT)")
    rows = ", ".join(f"({int(rng.integers(0, 8))}, "
                     f"{int(rng.integers(-40, 40))})" for _ in range(150))
    db.run(f"INSERT INTO t VALUES {rows}")
    got = sorted(db.query(
        "SELECT s.k, s.c FROM (SELECT k, count(*) AS c FROM t GROUP BY k) "
        "AS s WHERE s.k > 2 AND s.c > 1 + 1"))
    want = sorted(r for r in db.query(
        "SELECT k, count(*) FROM t GROUP BY k") if r[0] > 2 and r[1] > 2)
    assert got == want and len(got) > 0

    # pushdown also applies to streaming MVs (same planner entry)
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT s.k, s.c FROM "
           "(SELECT k, count(*) AS c FROM t GROUP BY k) AS s "
           "WHERE s.k > 2 AND s.c > 2")
    db.run("INSERT INTO t VALUES (3, 1), (3, 2), (3, 3)")
    got_mv = sorted(db.query("SELECT * FROM mv"))
    want2 = sorted(r for r in db.query(
        "SELECT k, count(*) FROM t GROUP BY k") if r[0] > 2 and r[1] > 2)
    assert got_mv == want2
