"""Arrow interop seam (arrow_impl.rs ToArrow/FromArrow analog) +
zero-copy guarantees.
"""
from decimal import Decimal

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

from risingwave_tpu.core import dtypes as T
from risingwave_tpu.core.arrow import (column_from_arrow, column_to_arrow,
                                       datachunk_from_arrow,
                                       datachunk_to_arrow,
                                       streamchunk_from_arrow,
                                       streamchunk_to_arrow, to_jax)
from risingwave_tpu.core.chunk import Column, DataChunk, Op, StreamChunk


def roundtrip(dtype, items):
    col = Column.from_list(dtype, items)
    arr = column_to_arrow(col)
    back = column_from_arrow(arr, dtype)
    assert [back.get(i) for i in range(len(back))] == \
        [col.get(i) for i in range(len(col))]
    return arr


class TestColumnRoundtrip:
    def test_fixed_width(self):
        roundtrip(T.INT64, [1, None, -5, 2**62])
        roundtrip(T.INT32, [1, 2, None])
        roundtrip(T.FLOAT64, [1.5, None, -0.25])
        roundtrip(T.BOOLEAN, [True, False, None])

    def test_temporal(self):
        arr = roundtrip(T.TIMESTAMP, [1704067200000000, None])
        assert pa.types.is_timestamp(arr.type)
        arr = roundtrip(T.DATE, [19723, None])
        assert pa.types.is_date32(arr.type)

    def test_strings_and_bytes(self):
        roundtrip(T.VARCHAR, ["a", None, "日本", ""])
        roundtrip(T.BYTEA, [b"\x00\x01", None])

    def test_decimal(self):
        arr = roundtrip(T.DECIMAL, [Decimal("1.5"), None, Decimal("-7")])
        assert pa.types.is_decimal(arr.type)

    def test_interval(self):
        from risingwave_tpu.core.dtypes import Interval
        roundtrip(T.INTERVAL, [Interval(1, 2, 3_000_000), None])


class TestZeroCopy:
    def test_int64_value_buffer_is_shared(self):
        vals = np.arange(1024, dtype=np.int64)
        col = Column(T.INT64, vals, np.ones(1024, bool))
        arr = column_to_arrow(col)
        assert arr.buffers()[1].address == vals.ctypes.data
        back = column_from_arrow(arr, T.INT64)
        assert back.values.ctypes.data == vals.ctypes.data

    def test_to_jax_device_seam(self):
        import jax.numpy as jnp
        col = Column(T.INT64, np.arange(16, dtype=np.int64),
                     np.ones(16, bool))
        x = to_jax(col)
        assert isinstance(x, jnp.ndarray) and int(x.sum()) == 120
        nullable = Column.from_list(T.INT64, [1, None])
        with pytest.raises(ValueError, match="NULL"):
            to_jax(nullable)


class TestChunks:
    def test_datachunk_roundtrip(self):
        dts = [T.INT64, T.VARCHAR]
        ch = DataChunk.from_rows(dts, [(1, "a"), (2, None), (None, "c")])
        batch = datachunk_to_arrow(ch, names=["k", "s"])
        assert batch.schema.names == ["k", "s"]
        back = datachunk_from_arrow(batch, dts)
        assert [tuple(back.columns[j].get(i) for j in range(2))
                for i in range(3)] == [(1, "a"), (2, None), (None, "c")]

    def test_streamchunk_roundtrip_preserves_ops(self):
        dts = [T.INT64, T.INT64]
        ch = StreamChunk.from_rows(dts, [
            (Op.INSERT, (1, 10)), (Op.DELETE, (2, 20)),
            (Op.UPDATE_DELETE, (3, 30)), (Op.UPDATE_INSERT, (3, 31))])
        batch = streamchunk_to_arrow(ch)
        back = streamchunk_from_arrow(batch, dts)
        assert list(back.ops) == list(ch.ops)
        assert back.columns[1].get(3) == 31
