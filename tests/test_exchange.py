"""Exchange layer: hash dispatch + merge alignment == single-actor result."""
import numpy as np
import pytest

from risingwave_tpu.core import Op, Schema, StreamChunk, dtypes as T
from risingwave_tpu.connectors import ListReader
from risingwave_tpu.expr import AggCall, InputRef
from risingwave_tpu.ops import (BarrierInjector, Channel, DispatchExecutor,
                                HashAggExecutor, MergeExecutor,
                                SourceExecutor, Watermark)
from risingwave_tpu.ops.message import Barrier

S = Schema.of(("k", T.INT64), ("v", T.INT64))


def make_chunks(rng, n_chunks=6, rows=64, keys=10):
    out = []
    for _ in range(n_chunks):
        ks = rng.integers(0, keys, rows)
        vs = rng.integers(0, 100, rows)
        out.append(StreamChunk.from_rows(
            S.dtypes, [(Op.INSERT, (int(k), int(v)))
                       for k, v in zip(ks, vs)]))
    return out


def run_parallel_agg(chunks, n_actors):
    """source -> hash dispatch -> N agg actors -> simple dispatch -> merge."""
    inj = BarrierInjector()
    src = SourceExecutor(S, ListReader(chunks), inj)
    mids = [Channel(capacity=1 << 20) for _ in range(n_actors)]
    disp = DispatchExecutor(src, mids, kind="hash", key_indices=[0])
    outs = []
    agg_disps = []
    for i in range(n_actors):
        merge_in = MergeExecutor([mids[i]], S, pumps=[disp])
        agg = HashAggExecutor(merge_in, [0],
                              [AggCall("count"),
                               AggCall("sum", InputRef(1, T.INT64))])
        out_ch = Channel(capacity=1 << 20)
        outs.append(out_ch)
        agg_disps.append(DispatchExecutor(agg, [out_ch], kind="simple"))
    final = MergeExecutor(outs, None, pumps=agg_disps)
    inj.inject()
    inj.inject_stop()
    state = {}
    barriers = 0
    for msg in final.execute():
        if isinstance(msg, StreamChunk):
            for op, r in msg.compact().op_rows():
                if op.is_insert:
                    state[r[0]] = r[1:]
                elif state.get(r[0]) == r[1:]:
                    del state[r[0]]
        elif isinstance(msg, Barrier):
            barriers += 1
    return state, barriers


def oracle(chunks):
    st = {}
    for c in chunks:
        for op, (k, v) in c.op_rows():
            cnt, sm = st.get(k, (0, 0))
            st[k] = (cnt + 1, sm + v)
    return st


def test_parallel_agg_matches_oracle():
    rng = np.random.default_rng(5)
    chunks = make_chunks(rng)
    exp = oracle(chunks)
    for n in (1, 2, 4):
        got, barriers = run_parallel_agg(chunks, n)
        got = {k: (c, int(s)) for k, (c, s) in got.items()}
        assert got == exp, f"n_actors={n}"
        assert barriers == 2  # initial + stop, each aligned to ONE barrier


def test_update_pair_split_degrades():
    """A U-/U+ pair whose halves hash to different outputs becomes D+I."""
    ch0, ch1 = Channel(), Channel()
    # find two keys landing on different outputs
    from risingwave_tpu.core.vnode import vnode_of_row, VNODE_COUNT
    k0 = 0
    k1 = next(k for k in range(1, 100)
              if (vnode_of_row([k]) * 2) // VNODE_COUNT !=
                 (vnode_of_row([k0]) * 2) // VNODE_COUNT)
    chunk = StreamChunk.from_rows(
        S.dtypes, [(Op.UPDATE_DELETE, (k0, 1)), (Op.UPDATE_INSERT, (k1, 2))])

    class OneShot:
        schema = S
        def execute(self):
            yield chunk
    d = DispatchExecutor(OneShot(), [ch0, ch1], kind="hash", key_indices=[0])
    d.pump_until_barrier()
    msgs = []
    for ch in (ch0, ch1):
        m = ch.recv()
        while m is not None:
            msgs.append(m)
            m = ch.recv()
    ops = [op for m in msgs for op, _ in m.compact().op_rows()]
    assert sorted(ops) == [Op.INSERT, Op.DELETE]


def test_broadcast_and_round_robin():
    ch = [Channel(), Channel()]
    chunk = StreamChunk.from_rows(S.dtypes, [(Op.INSERT, (1, 1))])

    class OneShot:
        schema = S
        def execute(self):
            yield chunk
            yield chunk
    d = DispatchExecutor(OneShot(), ch, kind="broadcast")
    d.pump_until_barrier()
    assert len(ch[0]) == 2 and len(ch[1]) == 2
    ch = [Channel(), Channel()]
    d = DispatchExecutor(OneShot(), ch, kind="round_robin")
    d.pump_until_barrier()
    assert len(ch[0]) == 1 and len(ch[1]) == 1


def test_merge_min_watermark():
    a, b = Channel(), Channel()
    m = MergeExecutor([a, b], S)
    from risingwave_tpu.ops.message import BarrierKind, EpochPair
    bar = Barrier(EpochPair(2, 1), BarrierKind.CHECKPOINT)
    stop = Barrier(EpochPair(3, 2), BarrierKind.CHECKPOINT)
    from risingwave_tpu.ops.message import Mutation, MutationKind
    stop.mutation = Mutation(MutationKind.STOP)
    a.send(Watermark(0, T.INT64, 10)); a.send(bar); a.send(stop)
    b.send(Watermark(0, T.INT64, 5)); b.send(bar); b.send(stop)
    msgs = list(m.execute())
    wms = [x for x in msgs if isinstance(x, Watermark)]
    assert [w.value for w in wms] == [5]
    assert sum(isinstance(x, Barrier) for x in msgs) == 2
