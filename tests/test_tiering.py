"""Tiered state beyond HBM (ISSUE 16).

The contract under test: the hot/cold state tier (device/tiering.py +
the FusedJob wiring) — cold-group demotion to per-node host ColdStores
off the commit phase, touch-promotion gated by Xor8 negative caches
probed per ingest window, `rw_key_skew` heavy hitters never demoted —
is gated by `DeviceConfig.state_tiering` / RW_STATE_TIERING, BIT-
IDENTICAL to the untiered run (row order included) at 1 and 8 shards,
keeps the device footprint inside the capacity clamp (no growth where
the untiered run grows), and every rebuild path (growth replay, restart
recovery, `fused.*` in-place recovery) reconstructs BOTH tiers.

The conftest pins RW_STATE_TIERING off suite-wide for compile budget;
every test here forces it back on via monkeypatch (read at CREATE
time). Promotion needs the host-ingest window (the recipes re-derive
candidate keys from the shipped host columns), so RW_HOST_INGEST goes
on too. RW_AGG_PRECOMBINE stays off — combined aggs are demotion-inert
by design (their input is the pre-combine output, not an ingest
lineage)."""
import os

import numpy as np
import pytest

from risingwave_tpu.config import DeviceConfig
from risingwave_tpu.sql import Database

N = 16384
N_SMALL = 8192
CHUNK = 32          # fused epoch = 64 * CHUNK = 2048 events

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT,"
           " price BIGINT, channel VARCHAR, url VARCHAR,"
           " date_time TIMESTAMP, extra VARCHAR) WITH"
           " (connector='nexmark', nexmark.table='bid',"
           " nexmark.max.events='{n}', nexmark.chunk.size='{c}'{kd})")
AUCTION_SRC = ("CREATE SOURCE auction (id BIGINT, item_name VARCHAR,"
               " description VARCHAR, initial_bid BIGINT,"
               " reserve BIGINT, date_time TIMESTAMP, expires TIMESTAMP,"
               " seller BIGINT, category BIGINT, extra VARCHAR) WITH"
               " (connector='nexmark', nexmark.table='auction',"
               " nexmark.max.events='{n}', nexmark.chunk.size='{c}')")

# q8-style unbounded key space: auction ids keep growing with the
# stream, so the live group set outruns any fixed capacity clamp
QA_MV = ("CREATE MATERIALIZED VIEW qa AS SELECT auction,"
         " count(*) AS n, sum(price) AS dol FROM bid GROUP BY auction")
Q3_MV = ("CREATE MATERIALIZED VIEW q3a AS SELECT b.auction, b.price,"
         " a.seller, a.category FROM bid b JOIN auction a"
         " ON b.auction = a.id WHERE b.price > 900")


def _arm(monkeypatch, high="0.35", low="0.15", skew="0"):
    monkeypatch.setenv("RW_STATE_TIERING", "1")
    monkeypatch.setenv("RW_HOST_INGEST", "1")
    monkeypatch.setenv("RW_TIER_HIGH_WATER", high)
    monkeypatch.setenv("RW_TIER_LOW_WATER", low)
    monkeypatch.setenv("RW_SKEW_STATS", skew)


def _run(mv_sql, name, shards, cap, tier, srcs=(BID_SRC,), kd=None,
         n=N, data_dir=None, keep=False, aot=False, arm=None,
         hbm_mb=4096, chunk=CHUNK):
    """One fused run to drain; `tier` overrides RW_STATE_TIERING for
    THIS create (the env is read at plan time)."""
    os.environ["RW_STATE_TIERING"] = tier
    db = Database(device=DeviceConfig(capacity=cap, mesh_shards=shards,
                                      aot_compile=aot, compile_buckets=0,
                                      hbm_budget_mb=hbm_mb),
                  data_dir=data_dir)
    kdc = f", nexmark.key.dist='{kd}'" if kd else ""
    for s in srcs:
        db.run(s.format(n=n, c=chunk, kd=kdc))
    db.run(mv_sql)
    job = db.catalog.get(name).runtime["fused_job"]
    assert job is not None, f"{name} must fuse"
    if arm is not None:
        from risingwave_tpu.utils import failpoint as fp
        fp.arm(*arm)
    try:
        for _ in range(n // (64 * chunk) + 3):
            db.tick()
        job.sync()
        db.tick()
    finally:
        if arm is not None:
            fp.reset()
    rows = db.query(f"SELECT * FROM {name}")
    return (rows, job, db) if keep else (rows, job, None)


def _store_dump(tm):
    """Canonical, comparison-stable image of every cold store: nested
    python scalars only (numpy scalars compare fine, but a canonical
    dump makes assertion diffs readable)."""
    def scal(v):
        return v.item() if hasattr(v, "item") else v

    def row(r):
        if isinstance(r, tuple) and len(r) == 2 \
                and isinstance(r[0], tuple):        # agg: (vals, touch)
            return (tuple(scal(v) for v in r[0]), scal(r[1]))
        if isinstance(r, list):                     # join: [(pk, vals, t)]
            return sorted((scal(pk), tuple(scal(v) for v in vs), scal(t))
                          for pk, vs, t in r)
        return tuple(scal(v) for v in r)            # mv: vals tuple

    out = {}
    for (node, side), store in tm.stores.items():
        out[(node, side)] = [
            sorted((scal(k), row(r)) for k, r in d.items())
            for d in store.rows]
    return out


# ---------------------------------------------------------------------------
# host-side policy units (fast, no device)
# ---------------------------------------------------------------------------


def test_select_cold_oldest_first_excludes_hot():
    from risingwave_tpu.device.tiering import select_cold
    keys = np.arange(100, dtype=np.int64)
    touch = np.arange(100, dtype=np.int64)[::-1].copy()  # key 99 oldest
    # no pressure below high water
    assert select_cold(keys, touch, 10, 100, (), 0xFF) is None
    # pressure: oldest-touched first, drains to low water
    os.environ["RW_TIER_HIGH_WATER"] = "0.5"
    os.environ["RW_TIER_LOW_WATER"] = "0.2"
    try:
        sel = select_cold(keys, touch, 100, 100, (), (1 << 40) - 1)
        assert sel is not None and len(sel) == 80       # 100 - 0.2*100
        assert sel[0] == 99 and sel[-1] == 20           # oldest first
        # heavy hitters are excluded even when stone cold
        sel = select_cold(keys, touch, 100, 100, (99, 98), (1 << 40) - 1)
        assert 99 not in sel and 98 not in sel
        assert sel[0] == 97
    finally:
        del os.environ["RW_TIER_HIGH_WATER"]
        del os.environ["RW_TIER_LOW_WATER"]


def test_xor8_build_none_and_store_fallback(monkeypatch):
    from risingwave_tpu.device.tiering import ColdStore, key_bytes
    from risingwave_tpu.state import hummock
    # a healthy filter: no false negatives, dedupe-hardened build
    keys = [key_bytes(k) for k in range(500)] + [key_bytes(7)] * 3
    f = hummock.Xor8.build(keys)
    assert f is not None, "duplicate keys must not fail the build"
    assert all(f.may_contain(key_bytes(k)) for k in range(500))
    # store with a live filter
    st = ColdStore(1)
    st.rows[0] = {k: ((k,), 0) for k in range(64)}
    st.rebuild_filter(0)
    assert st.filter_live[0]
    hits, probes, pos = st.probe(0, np.arange(32, 96, dtype=np.int64))
    assert sorted(hits) == list(range(32, 64)) and probes == 64
    # Xor8.build returning None degrades to always-probe, same hits
    monkeypatch.setattr(hummock.Xor8, "build",
                        staticmethod(lambda keys, seed=0: None))
    st2 = ColdStore(1)
    st2.rows[0] = dict(st.rows[0])
    st2.rebuild_filter(0)
    assert not st2.filter_live[0] and st2.filters[0] is None
    hits2, probes2, pos2 = st2.probe(0, np.arange(32, 96,
                                                  dtype=np.int64))
    assert sorted(hits2) == sorted(hits)      # correctness unchanged
    assert pos2 == len(hits2)                 # every probe paid the dict


# ---------------------------------------------------------------------------
# bit-identity + budget clamp (agg, 1 shard)
# ---------------------------------------------------------------------------


@pytest.mark.tiering
def test_agg_demotion_bit_identity_and_no_growth(monkeypatch):
    """The q8-style unbounded-key agg under a capacity clamp BELOW the
    live key count: the untiered run must grow; the tiered run demotes
    instead, stays inside the clamp, and serves the bit-identical MV
    (cold rows merged at SELECT time)."""
    _arm(monkeypatch)
    # 512-event fused epochs: demotion runs off every checkpoint, so
    # the drain keeps pace with new-key arrival (two-phase demotion is
    # one epoch behind — at 2048-event epochs the lag alone overshoots
    # a 512-slot clamp)
    r_off, j_off, _ = _run(QA_MV, "qa", 1, 512, "0", chunk=8)
    assert j_off.growth_replays >= 1, "untiered clamp must overflow"
    r_on, j_on, db = _run(QA_MV, "qa", 1, 512, "1", keep=True,
                          hbm_mb=1, chunk=8)
    assert r_off == r_on                 # bit-identical, order included
    assert len(r_on) > 512               # more groups than device slots
    assert j_on.growth_replays == 0, "the tier must absorb the overflow"
    agg = next(n for n in j_on.program.nodes
               if type(n).__name__ == "AggNode")
    assert agg.capacity == 512           # never grew past the clamp
    tm = j_on.tiering
    assert tm.counters["demotions"] > 0
    assert tm.counters["promotions"] > 0
    assert tm.counters["demote_events"] > 0
    assert tm.counters["filter_probes"] > 0
    # phases surfaced disjointly in the epoch profile
    assert j_on.profiler.totals.get("demote_d2h", 0.0) > 0.0
    assert j_on.profiler.totals.get("promote_h2d", 0.0) > 0.0
    prow = db.query("SELECT * FROM rw_epoch_profile")
    assert prow and len(prow[0]) == 13
    # HBM stayed inside the (1 MB) budget: the gauge is the acceptance
    # surface for "high-water <= budget"
    from risingwave_tpu.utils.metrics import REGISTRY
    text = REGISTRY.expose()
    vals = [float(line.rsplit(" ", 1)[1]) for line in text.splitlines()
            if line.startswith("rw_hbm_budget_utilization")
            and 'job="qa"' in line]
    assert vals and all(v <= 1.0 for v in vals), vals
    # rw_state_tiering reports the two tiers
    trows = db.query("SELECT * FROM rw_state_tiering")
    mine = [r for r in trows if r[0] == "qa"]
    assert mine and any(r[4] > 0 for r in mine)          # cold rows
    assert any(r[6] for r in mine)                       # promotable


@pytest.mark.mesh
@pytest.mark.tiering
def test_agg_demotion_bit_identity_mesh(monkeypatch):
    """Same contract at 8 mesh shards (per-shard capacities, per-shard
    cold stores, demoted rows return to the shard that owns them)."""
    _arm(monkeypatch)
    r1, _, _ = _run(QA_MV, "qa", 1, 4096, "0")
    r8, j8, _ = _run(QA_MV, "qa", 8, 256, "1")
    assert r1 == r8                      # bit-identical, order included
    tm = j8.tiering
    assert tm.counters["demotions"] > 0
    assert tm.counters["demote_events"] > 0
    assert j8.growth_replays == 0
    # the per-shard stores are genuinely spread, not one hot shard
    store = tm.store(next(p.node_idx for p in tm.plans), -1)
    assert sum(1 for d in store.rows if d) >= 2


# ---------------------------------------------------------------------------
# joins: both sides demote in lockstep, growth replay rebuilds the tier
# ---------------------------------------------------------------------------


@pytest.mark.tiering
def test_join_demotion_bit_identity_and_growth_replay(monkeypatch):
    """q3-shaped join under tier pressure: cold join keys demote BOTH
    build sides in one journal event, later bids for a demoted auction
    promote the pair back, and the mid-run capacity growth replay
    (the unbounded bid side outruns its clamp once) re-enacts the
    demotion journal — both tiers bit-identical through it all."""
    _arm(monkeypatch, high="0.1", low="0.02")
    r_off, _, _ = _run(Q3_MV, "q3a", 1, 4096, "0",
                       srcs=(BID_SRC, AUCTION_SRC), n=N_SMALL)
    r_on, job, _ = _run(Q3_MV, "q3a", 1, 4096, "1",
                        srcs=(BID_SRC, AUCTION_SRC), n=N_SMALL)
    assert r_off == r_on
    tm = job.tiering
    assert tm.counters["demotions"] > 0
    assert tm.counters["promotions"] > 0, \
        "a bid for a demoted auction must promote the pair back"
    assert tm.counters["filter_probes"] > 0
    assert job.growth_replays >= 1, \
        "this shape is sized to grow mid-run (replays the journal)"
    # both sides' stores saw traffic
    i = next(p.node_idx for p in tm.plans if p.kind == "join")
    assert len(tm.store(i, 0)) + len(tm.store(i, 1)) > 0


# ---------------------------------------------------------------------------
# durability: restart recovery + fused.* in-place recovery
# ---------------------------------------------------------------------------


@pytest.mark.tiering
def test_restart_recovery_rebuilds_both_tiers(monkeypatch, tmp_path):
    """A restart (new Database over the same data dir) replays the
    demotion journal beside the job state tables: the device resident
    tier AND the host cold stores come back bit-identical — same MV,
    same per-shard cold rows."""
    _arm(monkeypatch)
    d = str(tmp_path / "d")
    rows, job, db = _run(QA_MV, "qa", 1, 512, "1", data_dir=d,
                         keep=True)
    tm = job.tiering
    assert tm.counters["demote_events"] > 0
    want_stores = _store_dump(tm)
    assert any(any(s for s in shards) for shards in want_stores.values())
    assert os.path.exists(os.path.join(d, "tiering_journal_qa.jsonl"))
    del db, job
    os.environ["RW_STATE_TIERING"] = "1"
    db2 = Database(device=DeviceConfig(capacity=512, mesh_shards=1,
                                       aot_compile=False,
                                       compile_buckets=0), data_dir=d)
    job2 = db2.catalog.get("qa").runtime["fused_job"]
    assert job2.tiering is not None
    assert _store_dump(job2.tiering) == want_stores
    assert db2.query("SELECT * FROM qa") == rows


@pytest.mark.tiering
def test_inplace_recovery_failpoint_rebuilds_both_tiers(monkeypatch):
    """A fused.dispatch fault mid-run (fires once, after demotions have
    happened) heals in place: the history replay re-enacts the journal
    into fresh cold stores and the final MV is bit-identical to the
    untiered run."""
    _arm(monkeypatch)
    want, _, _ = _run(QA_MV, "qa", 1, 4096, "0")
    got, job, _ = _run(QA_MV, "qa", 1, 512, "1",
                       arm=("fused.dispatch", 1.0, 0, 1))
    assert job.recoveries == 1
    assert got == want
    tm = job.tiering
    assert tm.counters["demote_events"] > 0
    assert any(len(s) for s in tm.stores.values()), \
        "recovery must rebuild the cold tier, not just the device tier"


# ---------------------------------------------------------------------------
# policy: rw_key_skew heavy hitters never demote
# ---------------------------------------------------------------------------


@pytest.mark.tiering
def test_heavy_hitters_never_demoted(monkeypatch):
    """Under zipf:1.5 the rank-1 auction takes a dominant share of
    events; demoting it would make every window pay a promotion. The
    selector excludes the `rw_key_skew` top-K — the hot keys must never
    appear in any cold store shard, while plenty of tail keys do."""
    from risingwave_tpu.device.skew_stats import SK_KEY_MASK, hot_key_set
    _arm(monkeypatch, skew="1")
    _, job, _ = _run(QA_MV, "qa", 1, 512, "1", kd="zipf:1.5")
    tm = job.tiering
    assert tm.counters["demotions"] > 0
    i = next(p.node_idx for p in tm.plans)
    stats = job.program.node_stats(
        i, np.maximum(job._stat_totals, job._last_stats))
    hot = hot_key_set(stats)
    assert hot, "zipf:1.5 must register heavy hitters"
    demoted = set()
    for (node, _side), store in tm.stores.items():
        if node != i:
            continue
        for d in store.rows:
            demoted.update(int(k) & SK_KEY_MASK for k in d)
    assert demoted, "tail keys must still demote"
    assert not (set(hot) & demoted), \
        f"heavy hitters {set(hot) & demoted} were demoted"


# ---------------------------------------------------------------------------
# zero-compile adoption
# ---------------------------------------------------------------------------


@pytest.mark.aot
@pytest.mark.tiering
def test_demotion_promotion_zero_fresh_compile(monkeypatch):
    """Tier surgery adopts via rebuild-replay on the already-compiled
    node steps: across a window full of demotions AND promotions the
    compile service's counter must not move (the evict/promote jits are
    deliberately outside the service — its counters are the adoption
    assertion surface)."""
    from risingwave_tpu.device.compile_service import get_service
    _arm(monkeypatch)
    os.environ["RW_STATE_TIERING"] = "1"
    # capacity 1024 holds the whole run without growth (growth replays
    # legitimately recompile at the new capacity — not what we measure)
    db = Database(device=DeviceConfig(capacity=1024, mesh_shards=1,
                                      aot_compile=True,
                                      compile_buckets=0))
    db.run(BID_SRC.format(n=N, c=CHUNK, kd=""))
    db.run(QA_MV)
    job = db.catalog.get("qa").runtime["fused_job"]
    for _ in range(5):                   # first demote+promote cycle
        db.tick()                        # (high water ~358 keys; the
    # stream brings ~150/epoch, so pressure lands around tick 3-4 and
    # the two-phase enact one checkpoint later)
    job.sync()
    tm = job.tiering
    assert tm.counters["demote_events"] > 0
    svc = get_service()
    assert svc.wait_idle(120.0)
    before = svc.summary()["compiles"]
    ev0, pr0 = tm.counters["demote_events"], tm.counters["promotions"]
    for _ in range(N // (64 * CHUNK)):
        db.tick()
    job.sync()
    db.tick()
    assert tm.counters["demote_events"] > ev0
    assert tm.counters["promotions"] > pr0
    assert svc.wait_idle(120.0)
    assert svc.summary()["compiles"] == before, \
        "tier surgery must not trigger fresh node-step compiles"


# ---------------------------------------------------------------------------
# observability: rw_state_tiering + risectl tiering
# ---------------------------------------------------------------------------


@pytest.mark.tiering
def test_ctl_tiering_report(monkeypatch, tmp_path, capsys):
    from risingwave_tpu import ctl
    _arm(monkeypatch)
    d = str(tmp_path / "d")
    _, _, db = _run(QA_MV, "qa", 1, 512, "1", data_dir=d, keep=True)
    rc = ctl.main(["tiering", "--data-dir", d])
    out = capsys.readouterr().out
    assert rc == 0
    assert "qa" in out and "AggNode" in out and "resident" in out
    assert ctl.main(["tiering", "nosuch", "--data-dir", d]) == 1
    # DROP clears the demotion journal: a re-created MV under the same
    # name must not replay a predecessor's evictions
    jp = os.path.join(d, "tiering_journal_qa.jsonl")
    assert os.path.exists(jp)
    db.run("DROP MATERIALIZED VIEW qa")
    assert not os.path.exists(jp)
