"""Batch engine (SURVEY L5b): vectorized one-shot executors over a
pinned snapshot, translated from the planned stream tree. Reference:
src/batch/src/executor/mod.rs:47, batch_table snapshot reads."""
import numpy as np
import pytest

from risingwave_tpu.sql import Database


def _seed():
    db = Database()
    db.run("CREATE TABLE t (k INT, v BIGINT, s VARCHAR)")
    db.run("CREATE TABLE u (k INT, w BIGINT)")
    db.run("INSERT INTO t VALUES (1, 10, 'a'), (2, 20, 'b'), "
           "(1, 30, 'a'), (3, NULL, 'c')")
    db.run("INSERT INTO u VALUES (1, 100), (1, 101), (4, 400)")
    return db


def test_batch_plan_engages():
    """The batch translation actually runs for a plannable query."""
    import risingwave_tpu.batch as B
    calls = []
    orig = B.translate_stream_plan

    def spy(e, scan_of):
        r = orig(e, scan_of)
        calls.append(r)
        return r
    B.translate_stream_plan = spy
    try:
        db = _seed()
        db.query("SELECT k, sum(v) FROM t GROUP BY k")
    finally:
        B.translate_stream_plan = orig
    assert calls and calls[-1] is not None


def test_batch_agg_and_filters():
    db = _seed()
    assert sorted(db.query(
        "SELECT k, count(*), count(v), sum(v) FROM t GROUP BY k")) == \
        [(1, 2, 2, 40), (2, 1, 1, 20), (3, 1, 0, None)]
    assert db.query("SELECT sum(v) FROM t WHERE k = 1") == [(40,)]
    assert db.query("SELECT count(*) FROM t WHERE v > 15") == [(2,)]


def test_batch_simple_agg_empty_input():
    db = Database()
    db.run("CREATE TABLE e (x INT)")
    assert db.query("SELECT count(*) FROM e") == [(0,)]
    assert db.query("SELECT sum(x), max(x) FROM e") == [(None, None)]


def test_batch_joins():
    db = _seed()
    assert sorted(db.query(
        "SELECT t.k, t.v, u.w FROM t JOIN u ON t.k = u.k")) == \
        [(1, 10, 100), (1, 10, 101), (1, 30, 100), (1, 30, 101)]
    left = sorted(db.query(
        "SELECT t.k, u.w FROM t LEFT JOIN u ON t.k = u.k"), key=repr)
    assert (2, None) in left and (3, None) in left
    full = db.query("SELECT t.k, u.k FROM t FULL JOIN u ON t.k = u.k")
    assert (None, 4) in full
    cond = db.query("SELECT t.k, u.w FROM t JOIN u ON t.k = u.k "
                    "AND t.v < u.w")
    assert sorted(cond) == [(1, 100), (1, 100), (1, 101), (1, 101)]


def test_batch_distinct_and_subquery():
    db = _seed()
    assert sorted(db.query("SELECT DISTINCT k FROM t")) == [(1,), (2,), (3,)]
    assert db.query(
        "SELECT total FROM (SELECT k, sum(v) AS total FROM t GROUP BY k) "
        "AS s WHERE s.k = 1") == [(40,)]


def test_batch_distinct_aggregates():
    """DISTINCT aggregates dedup per group (review finding: the batch
    path ignored AggCall.distinct)."""
    db = Database()
    db.run("CREATE TABLE d (k INT, v BIGINT)")
    db.run("INSERT INTO d VALUES (1, 10), (1, 10), (1, 20), (2, 5), (2, 5)")
    assert sorted(db.query(
        "SELECT k, count(DISTINCT v), sum(DISTINCT v) FROM d GROUP BY k")) \
        == [(1, 2, 30), (2, 1, 5)]


def test_batch_matches_stream_fallback_on_random_data():
    """The batch pipeline and the replay-as-stream path must agree."""
    import risingwave_tpu.batch as B
    rng = np.random.default_rng(9)
    db = Database()
    db.run("CREATE TABLE r (a INT, b BIGINT)")
    rows = ", ".join(f"({int(rng.integers(0, 5))}, "
                     f"{int(rng.integers(-50, 50))})" for _ in range(200))
    db.run(f"INSERT INTO r VALUES {rows}")
    q = ("SELECT a, count(*), sum(b), min(b), max(b), avg(b) "
         "FROM r WHERE b <> 13 GROUP BY a HAVING count(*) > 2")
    fast = sorted(db.query(q), key=repr)
    orig = B.translate_stream_plan
    B.translate_stream_plan = lambda e, s: None      # force fallback
    try:
        slow = sorted(db.query(q), key=repr)
    finally:
        B.translate_stream_plan = orig
    assert fast == slow and len(fast) > 0
