"""Device MV table (REPLACE semantics) + fused datagen->agg->MV pipeline."""
import numpy as np
import jax
import jax.numpy as jnp

from risingwave_tpu.device import ReduceKind, batch_reduce, make_state, merge
from risingwave_tpu.device.agg_step import DeviceAggSpec
from risingwave_tpu.device.materialize import (make_mv_state,
                                               mv_apply_changes, mv_rows)
from risingwave_tpu.device.pipeline import bid_agg_epoch, make_bid_pipeline
from risingwave_tpu.device.sorted_state import EMPTY_KEY


def test_batch_reduce_replace_last_wins():
    keys = jnp.asarray([7, 7, 9, 7, 9], dtype=jnp.int64)
    mask = jnp.asarray([1, 1, 1, 1, 0], dtype=bool)
    vals = [jnp.asarray([10, 20, 30, 40, 50], dtype=jnp.int64)]
    uk, uv, uc = batch_reduce(keys, mask, vals, [ReduceKind.REPLACE])
    got = {int(k): int(v) for k, v in zip(np.asarray(uk), np.asarray(uv[0]))
           if k != EMPTY_KEY}
    assert got == {7: 40, 9: 30}  # arrival order wins, masked row ignored


def test_merge_replace_overwrites_state():
    st = make_state(8, [jnp.int64], [ReduceKind.REPLACE])
    dk = jnp.asarray([1, 2, int(EMPTY_KEY), int(EMPTY_KEY)], dtype=jnp.int64)
    dv = [jnp.asarray([100, 200, 0, 0], dtype=jnp.int64)]
    st, _ = merge(st, dk, dv, [ReduceKind.REPLACE], drop_dead=False)
    dv = [jnp.asarray([111, 0, 0, 0], dtype=jnp.int64)]
    st, _ = merge(st, dk, dv, [ReduceKind.REPLACE], drop_dead=False)
    n = int(st.count)
    got = {int(k): int(v) for k, v in
           zip(np.asarray(st.keys)[:n], np.asarray(st.vals[0])[:n])}
    assert got[1] == 111 and got[2] == 0


def test_mv_upsert_delete():
    mv = make_mv_state(8, [jnp.int64])
    keys = jnp.asarray([5, 6, int(EMPTY_KEY)], dtype=jnp.int64)
    ups = jnp.asarray([True, True, False])
    dels = jnp.zeros(3, bool)
    cols = [jnp.asarray([50, 60, 0], dtype=jnp.int64)]
    nulls = [jnp.zeros(3, bool)]
    mv, _ = mv_apply_changes(mv, keys, ups, dels, cols, nulls)
    k, c, nl = mv_rows(mv, [jnp.int64])
    assert list(k) == [5, 6] and list(c[0]) == [50, 60]
    # delete 5, update 6
    ups = jnp.asarray([False, True, False])
    dels = jnp.asarray([True, False, False])
    cols = [jnp.asarray([0, 66, 0], dtype=jnp.int64)]
    mv, _ = mv_apply_changes(mv, keys, ups, dels, cols, nulls)
    k, c, nl = mv_rows(mv, [jnp.int64])
    assert list(k) == [6] and list(c[0]) == [66]


def test_fused_pipeline_matches_host_recompute():
    spec = DeviceAggSpec.build(["count_star", "sum", "max"], [np.int64] * 3)
    agg, mv = make_bid_pipeline(spec, 1024)
    rng = jax.random.PRNGKey(3)
    mn = jnp.zeros((), jnp.int32)
    for _ in range(3):
        agg, mv, rng, mn = bid_agg_epoch(spec, 2048, 300, agg, mv, rng, mn)
    assert int(mn) <= 1024
    # replay generator on host
    from risingwave_tpu.device.datagen import gen_bids
    rng = jax.random.PRNGKey(3)
    cnt, tot, mx = {}, {}, {}
    for _ in range(3):
        a, p, rng = gen_bids(rng, 2048, 300)
        for key, price in zip(np.asarray(a).tolist(), np.asarray(p).tolist()):
            cnt[key] = cnt.get(key, 0) + 1
            tot[key] = tot.get(key, 0) + price
            mx[key] = max(mx.get(key, 0), price)
    keys, cols, nulls = mv_rows(mv, [c.acc_dtype for c in spec.calls])
    assert len(keys) == len(cnt)
    for i, key in enumerate(keys.tolist()):
        assert (cols[0][i], cols[1][i], cols[2][i]) == \
               (cnt[key], tot[key], mx[key])
