"""Regression tests for round-1 advisor findings (ADVICE.md)."""
import numpy as np

from risingwave_tpu.core import Op, Schema, StreamChunk, dtypes as T
from risingwave_tpu.core.chunk import Column
from risingwave_tpu.state import SpillStateStore


def test_rowid_layout_fits_63_bits_and_monotonic():
    from risingwave_tpu.ops.simple import RowIdGenExecutor
    from risingwave_tpu.ops.executor import Executor

    class _Stub(Executor):
        def __init__(self):
            super().__init__(Schema.of(("v", T.INT64)))

    gen = RowIdGenExecutor(_Stub(), row_id_index=1, shard=0x3FF)
    chunk = StreamChunk.from_rows([T.INT64],
                                  [(Op.INSERT, (i,)) for i in range(5000)])
    (out,) = list(gen.on_chunk(chunk))
    ids = out.columns[1].values.astype(np.int64)
    assert (ids > 0).all(), "row ids must not wrap negative"
    assert (np.diff(ids) > 0).all(), "row ids must be strictly increasing"
    # a second chunk continues above the first even after seq overflow
    (out2,) = list(gen.on_chunk(chunk))
    assert out2.columns[1].values.astype(np.int64)[0] > ids[-1]


def test_watermark_filter_drops_null_ts_once_watermark_set():
    from risingwave_tpu.ops.watermark import WatermarkFilterExecutor
    from risingwave_tpu.ops.executor import Executor

    class _Stub(Executor):
        def __init__(self):
            super().__init__(Schema.of(("ts", T.INT64)))

    f = WatermarkFilterExecutor(_Stub(), time_col=0, delay=0)
    c1 = StreamChunk.from_rows([T.INT64], [(Op.INSERT, (100,))])
    list(f.on_chunk(c1))
    assert f.watermark == 100
    c2 = StreamChunk.from_rows([T.INT64],
                               [(Op.INSERT, (None,)), (Op.INSERT, (150,))])
    outs = list(f.on_chunk(c2))
    rows = [r for ch in outs for _, r in ch.op_rows()]
    assert rows == [(150,)], "NULL event-time rows must be dropped " \
        "(reference filter `ts >= watermark` is not-true for NULL)"


def test_null_ts_passes_before_first_watermark():
    from risingwave_tpu.ops.watermark import WatermarkFilterExecutor
    from risingwave_tpu.ops.executor import Executor

    class _Stub(Executor):
        def __init__(self):
            super().__init__(Schema.of(("ts", T.INT64)))

    f = WatermarkFilterExecutor(_Stub(), time_col=0, delay=0)
    c = StreamChunk.from_rows([T.INT64], [(Op.INSERT, (None,))])
    outs = list(f.on_chunk(c))
    rows = [r for ch in outs for _, r in ch.op_rows()]
    assert rows == [(None,)]


def test_spill_store_future_epoch_delta_not_committed_early(tmp_path):
    """Data ingested for epoch N+1 must not become durable when committing
    epoch N (ADVICE: _deltas keyed by table only broke the 'uncommitted
    epochs vanish' contract)."""
    d = str(tmp_path)
    st = SpillStateStore(d)
    st.ingest_batch(1, [(b"a", (1,))], epoch=100)
    st.ingest_batch(1, [(b"b", (2,))], epoch=200)   # next epoch, early
    st.commit_epoch(100)
    st2 = SpillStateStore(d)
    assert st2.get(1, b"a") == (1,)
    assert st2.get(1, b"b") is None, \
        "epoch-200 delta leaked into the epoch-100 checkpoint"
    # ...and it IS durable once its own epoch commits
    st.commit_epoch(200)
    st3 = SpillStateStore(d)
    assert st3.get(1, b"b") == (2,)


def test_compaction_does_not_leak_uncommitted_future_epoch(tmp_path):
    """_compact must merge from durable runs, not the live memtable, or a
    future epoch's ingested-but-uncommitted rows become durable early."""
    d = str(tmp_path)
    st = SpillStateStore(d)
    st.ingest_batch(1, [(b"future", (99,))], epoch=1000)  # not committed
    for ep in range(1, 12):   # push past COMPACT_THRESHOLD
        st.ingest_batch(1, [(f"k{ep}".encode(), (ep,))], epoch=ep)
        st.commit_epoch(ep)
    st2 = SpillStateStore(d)  # crash before epoch 1000 commits
    assert st2.get(1, b"future") is None, \
        "compaction leaked an uncommitted future-epoch row into the base"
    assert st2.get(1, b"k5") == (5,)


def test_device_agg_key_at_sentinel_not_lost():
    import jax.numpy as jnp
    from risingwave_tpu.device.agg_step import DeviceAggSpec, DeviceHashAgg
    from risingwave_tpu.device.sorted_state import EMPTY_KEY
    spec = DeviceAggSpec.build(["count_star"], [np.int64])
    agg = DeviceHashAgg(spec, capacity=16)
    keys = np.array([np.iinfo(np.int64).max, 5], dtype=np.int64)
    vals = np.array([1, 1], dtype=np.int64)
    agg.push_rows(keys, np.ones(2, np.int32), [(vals, np.ones(2, bool))])
    ch = agg.flush_epoch()
    assert int(ch["count"]) == 2, "int64-max key must survive (remapped)"


def test_hash64_never_hits_device_empty_sentinel():
    from risingwave_tpu.core.vnode import column_hash64, hash_columns64
    from risingwave_tpu.device.sorted_state import EMPTY_KEY
    col = Column.from_list(T.VARCHAR, [f"s{i}" for i in range(1000)] + [None])
    h = column_hash64(col).view(np.int64)
    assert not (h == EMPTY_KEY).any()
    h2 = hash_columns64([col, col]).view(np.int64)
    assert not (h2 == EMPTY_KEY).any()
