"""Regression tests for round-1 advisor findings (ADVICE.md)."""
import numpy as np

from risingwave_tpu.core import Op, Schema, StreamChunk, dtypes as T
from risingwave_tpu.core.chunk import Column
from risingwave_tpu.state import SpillStateStore


def test_rowid_layout_fits_63_bits_and_monotonic():
    from risingwave_tpu.ops.simple import RowIdGenExecutor
    from risingwave_tpu.ops.executor import Executor

    class _Stub(Executor):
        def __init__(self):
            super().__init__(Schema.of(("v", T.INT64)))

    gen = RowIdGenExecutor(_Stub(), row_id_index=1, shard=0x3FF)
    chunk = StreamChunk.from_rows([T.INT64],
                                  [(Op.INSERT, (i,)) for i in range(5000)])
    (out,) = list(gen.on_chunk(chunk))
    ids = out.columns[1].values.astype(np.int64)
    assert (ids > 0).all(), "row ids must not wrap negative"
    assert (np.diff(ids) > 0).all(), "row ids must be strictly increasing"
    # a second chunk continues above the first even after seq overflow
    (out2,) = list(gen.on_chunk(chunk))
    assert out2.columns[1].values.astype(np.int64)[0] > ids[-1]


def test_watermark_filter_drops_null_ts_once_watermark_set():
    from risingwave_tpu.ops.watermark import WatermarkFilterExecutor
    from risingwave_tpu.ops.executor import Executor

    class _Stub(Executor):
        def __init__(self):
            super().__init__(Schema.of(("ts", T.INT64)))

    f = WatermarkFilterExecutor(_Stub(), time_col=0, delay=0)
    c1 = StreamChunk.from_rows([T.INT64], [(Op.INSERT, (100,))])
    list(f.on_chunk(c1))
    assert f.watermark == 100
    c2 = StreamChunk.from_rows([T.INT64],
                               [(Op.INSERT, (None,)), (Op.INSERT, (150,))])
    outs = list(f.on_chunk(c2))
    rows = [r for ch in outs for _, r in ch.op_rows()]
    assert rows == [(150,)], "NULL event-time rows must be dropped " \
        "(reference filter `ts >= watermark` is not-true for NULL)"


def test_null_ts_passes_before_first_watermark():
    from risingwave_tpu.ops.watermark import WatermarkFilterExecutor
    from risingwave_tpu.ops.executor import Executor

    class _Stub(Executor):
        def __init__(self):
            super().__init__(Schema.of(("ts", T.INT64)))

    f = WatermarkFilterExecutor(_Stub(), time_col=0, delay=0)
    c = StreamChunk.from_rows([T.INT64], [(Op.INSERT, (None,))])
    outs = list(f.on_chunk(c))
    rows = [r for ch in outs for _, r in ch.op_rows()]
    assert rows == [(None,)]


def test_spill_store_future_epoch_delta_not_committed_early(tmp_path):
    """Data ingested for epoch N+1 must not become durable when committing
    epoch N (ADVICE: _deltas keyed by table only broke the 'uncommitted
    epochs vanish' contract)."""
    d = str(tmp_path)
    st = SpillStateStore(d)
    st.ingest_batch(1, [(b"a", (1,))], epoch=100)
    st.ingest_batch(1, [(b"b", (2,))], epoch=200)   # next epoch, early
    st.commit_epoch(100)
    st2 = SpillStateStore(d)
    assert st2.get(1, b"a") == (1,)
    assert st2.get(1, b"b") is None, \
        "epoch-200 delta leaked into the epoch-100 checkpoint"
    # ...and it IS durable once its own epoch commits
    st.commit_epoch(200)
    st3 = SpillStateStore(d)
    assert st3.get(1, b"b") == (2,)


def test_compaction_does_not_leak_uncommitted_future_epoch(tmp_path):
    """_compact must merge from durable runs, not the live memtable, or a
    future epoch's ingested-but-uncommitted rows become durable early."""
    d = str(tmp_path)
    st = SpillStateStore(d)
    st.ingest_batch(1, [(b"future", (99,))], epoch=1000)  # not committed
    for ep in range(1, 12):   # push past COMPACT_THRESHOLD
        st.ingest_batch(1, [(f"k{ep}".encode(), (ep,))], epoch=ep)
        st.commit_epoch(ep)
    st2 = SpillStateStore(d)  # crash before epoch 1000 commits
    assert st2.get(1, b"future") is None, \
        "compaction leaked an uncommitted future-epoch row into the base"
    assert st2.get(1, b"k5") == (5,)


def test_device_agg_key_at_sentinel_not_lost():
    import jax.numpy as jnp
    from risingwave_tpu.device.agg_step import DeviceAggSpec, DeviceHashAgg
    from risingwave_tpu.device.sorted_state import EMPTY_KEY
    spec = DeviceAggSpec.build(["count_star"], [np.int64])
    agg = DeviceHashAgg(spec, capacity=16)
    keys = np.array([np.iinfo(np.int64).max, 5], dtype=np.int64)
    vals = np.array([1, 1], dtype=np.int64)
    agg.push_rows(keys, np.ones(2, np.int32), [(vals, np.ones(2, bool))])
    ch = agg.flush_epoch()
    assert int(ch["count"]) == 2, "int64-max key must survive (remapped)"


def test_hash64_never_hits_device_empty_sentinel():
    from risingwave_tpu.core.vnode import column_hash64, hash_columns64
    from risingwave_tpu.device.sorted_state import EMPTY_KEY
    col = Column.from_list(T.VARCHAR, [f"s{i}" for i in range(1000)] + [None])
    h = column_hash64(col).view(np.int64)
    assert not (h == EMPTY_KEY).any()
    h2 = hash_columns64([col, col]).view(np.int64)
    assert not (h2 == EMPTY_KEY).any()


# ---------------------------------------------------------------------------
# round-3 advisor findings: exchange_net abort semantics, permit refunds,
# U-pair frame splits, ctl device-mode auto-detection, UDF gating
# ---------------------------------------------------------------------------


def test_netchannel_abort_unblocks_sender_and_fails_wait_drained():
    """A consumer that dies mid-stream must not hang a producer blocked on
    channel capacity, and wait_drained must report the abort."""
    import socket
    import struct
    import threading
    import time

    from risingwave_tpu.runtime.exchange_net import (ExchangeServer,
                                                     _send_frame)

    server = ExchangeServer()
    ch = server.register(7, [T.INT64], capacity=2)
    # connect, handshake, read nothing, then die
    sock = socket.create_connection(server.addr)
    _send_frame(sock, b"H", struct.pack(">H", 7))
    time.sleep(0.1)

    chunk = StreamChunk.from_rows([T.INT64], [(Op.INSERT, (1,))])
    done = threading.Event()

    def producer():
        for _ in range(600):           # >> capacity + socket buffers
            ch.send(chunk)
        done.set()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.3)
    assert not done.is_set(), \
        "producer must be wedged on channel capacity before the kill"
    sock.close()                       # consumer vanishes
    t.join(timeout=10)
    assert done.is_set(), "producer must unblock when the consumer dies"
    assert ch.aborted
    assert server.wait_drained(timeout=5) is False, \
        "an aborted stream is not 'fully delivered'"
    server.close()


def test_zero_row_chunk_frames_refund_permits():
    """Every C frame returns a permit, even one that decodes to zero rows
    (e.g. a fully-invisible chunk) — otherwise credit drains away."""
    from risingwave_tpu.core.schema import Schema
    from risingwave_tpu.ops.message import Barrier, BarrierKind
    from risingwave_tpu.core.epoch import EpochPair
    from risingwave_tpu.runtime.exchange_net import (DEFAULT_PERMITS,
                                                     ExchangeServer,
                                                     RemoteInput)

    server = ExchangeServer()
    ch = server.register(3, [T.INT64])
    # far more all-invisible chunks than the initial credit
    empty = StreamChunk(
        np.array([0], dtype=np.int8),
        [Column(T.INT64, np.array([5], dtype=np.int64))],
        visibility=np.array([False]))
    for _ in range(2 * DEFAULT_PERMITS):
        ch.send(empty)
    ch.send(Barrier(EpochPair(2, 1), BarrierKind.CHECKPOINT, None))
    ch.close()

    inp = RemoteInput(server.addr, 3, Schema.of(("v", T.INT64)))
    msgs = list(inp.execute())      # hangs forever if permits leak
    assert any(isinstance(m, Barrier) for m in msgs)
    assert server.wait_drained(timeout=5) is True
    server.close()


def test_update_pair_straddling_frame_split_degrades_to_delete_insert():
    from risingwave_tpu.core.chunk import StreamChunkBuilder
    from risingwave_tpu.runtime.exchange_net import (MAX_FRAME_ROWS,
                                                     decode_chunk,
                                                     encode_chunk_frames)

    n_pairs = MAX_FRAME_ROWS // 2 + 2    # odd-aligns one pair on the split
    builder = StreamChunkBuilder([T.INT64], max_chunk_size=1 << 22)
    for i in range(n_pairs):
        builder.append_row(Op.UPDATE_DELETE, (i,))
        builder.append_row(Op.UPDATE_INSERT, (i,))
    (chunk,) = builder.drain()
    frames = encode_chunk_frames(chunk, [T.INT64])
    assert len(frames) > 1
    decoded = [decode_chunk(f, [T.INT64]) for f in frames]

    def vis_ops(d):
        vis = d.visibility if d.visibility is not None \
            else np.ones(d.capacity, dtype=bool)
        return [Op(int(o)) for o, v in zip(d.ops, vis) if v]

    for d in decoded:
        ops = vis_ops(d)
        # no frame may end U- or begin with a dangling U+
        assert not (ops and ops[-1] == Op.UPDATE_DELETE)
        assert not (ops and ops[0] == Op.UPDATE_INSERT)
    # multiset of (op-effect, value) is preserved: U-/U+ became D/I
    total_del = sum(1 for d in decoded for o in vis_ops(d) if o.is_delete)
    assert total_del == n_pairs


def test_ctl_dump_metrics_open_device_data_dir(tmp_path, monkeypatch):
    """dump/metrics on a device-mode data dir must adopt its policy (and
    not stamp markers onto unmarked directories)."""
    from risingwave_tpu.config import DeviceConfig
    from risingwave_tpu.ctl import main as ctl_main
    from risingwave_tpu.sql import Database

    d = str(tmp_path / "data")
    db = Database(data_dir=d, device=DeviceConfig(capacity=64))
    db.run("CREATE TABLE t (v BIGINT)")
    db.run("INSERT INTO t VALUES (1), (2)")
    db.tick()
    del db

    rc = ctl_main(["dump", "t", "--data-dir", d])
    assert rc == 0
    rc = ctl_main(["metrics", "--data-dir", d])
    assert rc == 0


def test_pgwire_rejects_embedded_udf_by_default():
    """A network client must not be able to exec() code in the server
    process unless the operator opted in."""
    from risingwave_tpu.pgwire import PgServer
    from risingwave_tpu.sql import Database
    from tests.test_pgwire import MiniClient

    db = Database()
    server = PgServer(db).start()
    try:
        c = MiniClient(server.host, server.port)
        c.startup()
        msgs = c.query("CREATE FUNCTION boom(x int) RETURNS int"
                       " LANGUAGE python AS $$\ndef boom(x):\n"
                       "    return x\n$$")
        assert any(t == b"E" for t, _ in msgs), "UDF must be rejected"
        # the SAME db's local (in-process) API stays ungated — the gate is
        # per-connection, not a global flag stamped onto the Database
        db.run("CREATE FUNCTION twice(x int) RETURNS int LANGUAGE python"
               " AS $$\ndef twice(x):\n    return 2 * x\n$$")
    finally:
        server.stop()
    # and an opted-in server accepts it
    db3 = Database()
    server3 = PgServer(db3, enable_embedded_udf=True).start()
    try:
        c = MiniClient(server3.host, server3.port)
        c.startup()
        msgs = c.query("CREATE FUNCTION thrice(x int) RETURNS int"
                       " LANGUAGE python AS $$\ndef thrice(x):\n"
                       "    return 3 * x\n$$")
        assert not any(t == b"E" for t, _ in msgs)
    finally:
        server3.stop()
