"""Regression tests for round-1 advisor findings (ADVICE.md)."""
import numpy as np
import pytest

from risingwave_tpu.core import Op, Schema, StreamChunk, dtypes as T
from risingwave_tpu.core.chunk import Column
from risingwave_tpu.state import SpillStateStore


def test_rowid_layout_fits_63_bits_and_monotonic():
    from risingwave_tpu.ops.simple import RowIdGenExecutor
    from risingwave_tpu.ops.executor import Executor

    class _Stub(Executor):
        def __init__(self):
            super().__init__(Schema.of(("v", T.INT64)))

    gen = RowIdGenExecutor(_Stub(), row_id_index=1, shard=0x3FF)
    chunk = StreamChunk.from_rows([T.INT64],
                                  [(Op.INSERT, (i,)) for i in range(5000)])
    (out,) = list(gen.on_chunk(chunk))
    ids = out.columns[1].values.astype(np.int64)
    assert (ids > 0).all(), "row ids must not wrap negative"
    assert (np.diff(ids) > 0).all(), "row ids must be strictly increasing"
    # a second chunk continues above the first even after seq overflow
    (out2,) = list(gen.on_chunk(chunk))
    assert out2.columns[1].values.astype(np.int64)[0] > ids[-1]


def test_watermark_filter_drops_null_ts_once_watermark_set():
    from risingwave_tpu.ops.watermark import WatermarkFilterExecutor
    from risingwave_tpu.ops.executor import Executor

    class _Stub(Executor):
        def __init__(self):
            super().__init__(Schema.of(("ts", T.INT64)))

    f = WatermarkFilterExecutor(_Stub(), time_col=0, delay=0)
    c1 = StreamChunk.from_rows([T.INT64], [(Op.INSERT, (100,))])
    list(f.on_chunk(c1))
    assert f.watermark == 100
    c2 = StreamChunk.from_rows([T.INT64],
                               [(Op.INSERT, (None,)), (Op.INSERT, (150,))])
    outs = list(f.on_chunk(c2))
    rows = [r for ch in outs for _, r in ch.op_rows()]
    assert rows == [(150,)], "NULL event-time rows must be dropped " \
        "(reference filter `ts >= watermark` is not-true for NULL)"


def test_null_ts_passes_before_first_watermark():
    from risingwave_tpu.ops.watermark import WatermarkFilterExecutor
    from risingwave_tpu.ops.executor import Executor

    class _Stub(Executor):
        def __init__(self):
            super().__init__(Schema.of(("ts", T.INT64)))

    f = WatermarkFilterExecutor(_Stub(), time_col=0, delay=0)
    c = StreamChunk.from_rows([T.INT64], [(Op.INSERT, (None,))])
    outs = list(f.on_chunk(c))
    rows = [r for ch in outs for _, r in ch.op_rows()]
    assert rows == [(None,)]


def test_spill_store_future_epoch_delta_not_committed_early(tmp_path):
    """Data ingested for epoch N+1 must not become durable when committing
    epoch N (ADVICE: _deltas keyed by table only broke the 'uncommitted
    epochs vanish' contract)."""
    d = str(tmp_path)
    st = SpillStateStore(d)
    st.ingest_batch(1, [(b"a", (1,))], epoch=100)
    st.ingest_batch(1, [(b"b", (2,))], epoch=200)   # next epoch, early
    st.commit_epoch(100)
    st2 = SpillStateStore(d)
    assert st2.get(1, b"a") == (1,)
    assert st2.get(1, b"b") is None, \
        "epoch-200 delta leaked into the epoch-100 checkpoint"
    # ...and it IS durable once its own epoch commits
    st.commit_epoch(200)
    st3 = SpillStateStore(d)
    assert st3.get(1, b"b") == (2,)


def test_compaction_does_not_leak_uncommitted_future_epoch(tmp_path):
    """_compact must merge from durable runs, not the live memtable, or a
    future epoch's ingested-but-uncommitted rows become durable early."""
    d = str(tmp_path)
    st = SpillStateStore(d)
    st.ingest_batch(1, [(b"future", (99,))], epoch=1000)  # not committed
    for ep in range(1, 12):   # push past COMPACT_THRESHOLD
        st.ingest_batch(1, [(f"k{ep}".encode(), (ep,))], epoch=ep)
        st.commit_epoch(ep)
    st2 = SpillStateStore(d)  # crash before epoch 1000 commits
    assert st2.get(1, b"future") is None, \
        "compaction leaked an uncommitted future-epoch row into the base"
    assert st2.get(1, b"k5") == (5,)


def test_device_agg_key_at_sentinel_not_lost():
    import jax.numpy as jnp
    from risingwave_tpu.device.agg_step import DeviceAggSpec, DeviceHashAgg
    from risingwave_tpu.device.sorted_state import EMPTY_KEY
    spec = DeviceAggSpec.build(["count_star"], [np.int64])
    agg = DeviceHashAgg(spec, capacity=16)
    keys = np.array([np.iinfo(np.int64).max, 5], dtype=np.int64)
    vals = np.array([1, 1], dtype=np.int64)
    agg.push_rows(keys, np.ones(2, np.int32), [(vals, np.ones(2, bool))])
    ch = agg.flush_epoch()
    assert int(ch["count"]) == 2, "int64-max key must survive (remapped)"


def test_hash64_never_hits_device_empty_sentinel():
    from risingwave_tpu.core.vnode import column_hash64, hash_columns64
    from risingwave_tpu.device.sorted_state import EMPTY_KEY
    col = Column.from_list(T.VARCHAR, [f"s{i}" for i in range(1000)] + [None])
    h = column_hash64(col).view(np.int64)
    assert not (h == EMPTY_KEY).any()
    h2 = hash_columns64([col, col]).view(np.int64)
    assert not (h2 == EMPTY_KEY).any()


# ---------------------------------------------------------------------------
# round-3 advisor findings: exchange_net abort semantics, permit refunds,
# U-pair frame splits, ctl device-mode auto-detection, UDF gating
# ---------------------------------------------------------------------------


def test_netchannel_abort_unblocks_sender_and_fails_wait_drained():
    """A consumer that dies mid-stream must not hang a producer blocked on
    channel capacity, and wait_drained must report the abort."""
    import socket
    import struct
    import threading
    import time

    from risingwave_tpu.runtime.exchange_net import (ExchangeServer,
                                                     _send_frame)

    server = ExchangeServer()
    ch = server.register(7, [T.INT64], capacity=2)
    # connect, handshake, read nothing, then die
    sock = socket.create_connection(server.addr)
    _send_frame(sock, b"H", struct.pack(">H", 7))
    time.sleep(0.1)

    chunk = StreamChunk.from_rows([T.INT64], [(Op.INSERT, (1,))])
    done = threading.Event()

    def producer():
        for _ in range(600):           # >> capacity + socket buffers
            ch.send(chunk)
        done.set()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.3)
    assert not done.is_set(), \
        "producer must be wedged on channel capacity before the kill"
    sock.close()                       # consumer vanishes
    t.join(timeout=10)
    assert done.is_set(), "producer must unblock when the consumer dies"
    assert ch.aborted
    assert server.wait_drained(timeout=5) is False, \
        "an aborted stream is not 'fully delivered'"
    server.close()


def test_zero_row_chunk_frames_refund_permits():
    """Every C frame returns a permit, even one that decodes to zero rows
    (e.g. a fully-invisible chunk) — otherwise credit drains away."""
    from risingwave_tpu.core.schema import Schema
    from risingwave_tpu.ops.message import Barrier, BarrierKind
    from risingwave_tpu.core.epoch import EpochPair
    from risingwave_tpu.runtime.exchange_net import (DEFAULT_PERMITS,
                                                     ExchangeServer,
                                                     RemoteInput)

    server = ExchangeServer()
    ch = server.register(3, [T.INT64])
    # far more all-invisible chunks than the initial credit
    empty = StreamChunk(
        np.array([0], dtype=np.int8),
        [Column(T.INT64, np.array([5], dtype=np.int64))],
        visibility=np.array([False]))
    for _ in range(2 * DEFAULT_PERMITS):
        ch.send(empty)
    ch.send(Barrier(EpochPair(2, 1), BarrierKind.CHECKPOINT, None))
    ch.close()

    inp = RemoteInput(server.addr, 3, Schema.of(("v", T.INT64)))
    msgs = list(inp.execute())      # hangs forever if permits leak
    assert any(isinstance(m, Barrier) for m in msgs)
    assert server.wait_drained(timeout=5) is True
    server.close()


def test_update_pair_straddling_frame_split_degrades_to_delete_insert():
    from risingwave_tpu.core.chunk import StreamChunkBuilder
    from risingwave_tpu.runtime.exchange_net import (MAX_FRAME_ROWS,
                                                     decode_chunk,
                                                     encode_chunk_frames)

    n_pairs = MAX_FRAME_ROWS // 2 + 2    # odd-aligns one pair on the split
    builder = StreamChunkBuilder([T.INT64], max_chunk_size=1 << 22)
    for i in range(n_pairs):
        builder.append_row(Op.UPDATE_DELETE, (i,))
        builder.append_row(Op.UPDATE_INSERT, (i,))
    (chunk,) = builder.drain()
    frames = encode_chunk_frames(chunk, [T.INT64])
    assert len(frames) > 1
    decoded = [decode_chunk(f, [T.INT64]) for f in frames]

    def vis_ops(d):
        vis = d.visibility if d.visibility is not None \
            else np.ones(d.capacity, dtype=bool)
        return [Op(int(o)) for o, v in zip(d.ops, vis) if v]

    for d in decoded:
        ops = vis_ops(d)
        # no frame may end U- or begin with a dangling U+
        assert not (ops and ops[-1] == Op.UPDATE_DELETE)
        assert not (ops and ops[0] == Op.UPDATE_INSERT)
    # multiset of (op-effect, value) is preserved: U-/U+ became D/I
    total_del = sum(1 for d in decoded for o in vis_ops(d) if o.is_delete)
    assert total_del == n_pairs


def test_ctl_dump_metrics_open_device_data_dir(tmp_path, monkeypatch):
    """dump/metrics on a device-mode data dir must adopt its policy (and
    not stamp markers onto unmarked directories)."""
    from risingwave_tpu.config import DeviceConfig
    from risingwave_tpu.ctl import main as ctl_main
    from risingwave_tpu.sql import Database

    d = str(tmp_path / "data")
    db = Database(data_dir=d, device=DeviceConfig(capacity=64))
    db.run("CREATE TABLE t (v BIGINT)")
    db.run("INSERT INTO t VALUES (1), (2)")
    db.tick()
    del db

    rc = ctl_main(["dump", "t", "--data-dir", d])
    assert rc == 0
    rc = ctl_main(["metrics", "--data-dir", d])
    assert rc == 0


def test_pgwire_rejects_embedded_udf_by_default():
    """A network client must not be able to exec() code in the server
    process unless the operator opted in."""
    from risingwave_tpu.pgwire import PgServer
    from risingwave_tpu.sql import Database
    from tests.test_pgwire import MiniClient

    db = Database()
    server = PgServer(db).start()
    try:
        c = MiniClient(server.host, server.port)
        c.startup()
        msgs = c.query("CREATE FUNCTION boom(x int) RETURNS int"
                       " LANGUAGE python AS $$\ndef boom(x):\n"
                       "    return x\n$$")
        assert any(t == b"E" for t, _ in msgs), "UDF must be rejected"
        # the SAME db's local (in-process) API stays ungated — the gate is
        # per-connection, not a global flag stamped onto the Database
        db.run("CREATE FUNCTION twice(x int) RETURNS int LANGUAGE python"
               " AS $$\ndef twice(x):\n    return 2 * x\n$$")
    finally:
        server.stop()
    # and an opted-in server accepts it
    db3 = Database()
    server3 = PgServer(db3, enable_embedded_udf=True).start()
    try:
        c = MiniClient(server3.host, server3.port)
        c.startup()
        msgs = c.query("CREATE FUNCTION thrice(x int) RETURNS int"
                       " LANGUAGE python AS $$\ndef thrice(x):\n"
                       "    return 3 * x\n$$")
        assert not any(t == b"E" for t, _ in msgs)
    finally:
        server3.stop()


# ---------------------------------------------------------------------------
# round-5 advisor findings (ADVICE.md) — satellites of the failpoint PR
# ---------------------------------------------------------------------------


def test_lag_lead_honor_constant_offset():
    """planner.py used to drop f.args[1] silently, so lead(v,2) computed
    lead(v,1)."""
    from risingwave_tpu.sql import Database
    db = Database()
    db.run("CREATE TABLE t (k BIGINT, ts BIGINT, v BIGINT)")
    db.run("CREATE MATERIALIZED VIEW m AS SELECT ts,"
           " lead(v, 2) OVER (PARTITION BY k ORDER BY ts) AS ld,"
           " lag(v, 3) OVER (PARTITION BY k ORDER BY ts) AS lg FROM t")
    db.run("INSERT INTO t VALUES (1, 1, 10), (1, 2, 20), (1, 3, 30),"
           " (1, 4, 40), (1, 5, 50)")
    for _ in range(3):
        db.tick()
    assert sorted(db.query("SELECT * FROM m")) == [
        (1, 30, None), (2, 40, None), (3, 50, None),
        (4, None, 10), (5, None, 20)]
    # 1-arg form stays offset 1
    db.run("CREATE MATERIALIZED VIEW m1 AS SELECT ts,"
           " lag(v) OVER (PARTITION BY k ORDER BY ts) AS lg FROM t")
    for _ in range(3):
        db.tick()
    assert sorted(db.query("SELECT * FROM m1")) == [
        (1, None), (2, 10), (3, 20), (4, 30), (5, 40)]


def test_lag_lead_reject_unsupported_offsets():
    import pytest
    from risingwave_tpu.sql import Database
    db = Database()
    db.run("CREATE TABLE t (k BIGINT, ts BIGINT, v BIGINT)")
    with pytest.raises(ValueError, match="constant"):
        db.run("CREATE MATERIALIZED VIEW m AS SELECT"
               " lag(v, v) OVER (PARTITION BY k ORDER BY ts) FROM t")
    with pytest.raises(ValueError, match="3-arg"):
        db.run("CREATE MATERIALIZED VIEW m AS SELECT"
               " lag(v, 1, 0) OVER (PARTITION BY k ORDER BY ts) FROM t")


def test_xor8_positions_cover_large_segments():
    """hummock.py:114 masked hashes to 20 bits, so filter slots >= 2**20
    were unreachable and large-run construction reliably failed."""
    from risingwave_tpu.state.hummock import Xor8
    seg = 1 << 21
    seen_hi = 0
    for i in range(4096):
        h = Xor8._h(b"key-%d" % i, 0)
        _, p0, p1, p2 = Xor8._positions(h, seg)
        assert p0 < seg and seg <= p1 < 2 * seg and 2 * seg <= p2 < 3 * seg
        seen_hi = max(seen_hi, p0, p1 - seg, p2 - 2 * seg)
        # the legacy layout provably cannot reach slots >= 2**20
        _, q0, q1, q2 = Xor8._positions(h, seg, ver=0)
        assert q0 < (1 << 20) and q1 - seg < (1 << 20) \
            and q2 - 2 * seg < (1 << 20)
    assert seen_hi >= (1 << 20), \
        "full-width positions must reach the upper half of the segment"


def test_xor8_build_and_roundtrip_mid_size():
    from risingwave_tpu.state.hummock import Xor8
    keys = [b"k%08d" % i for i in range(100_000)]
    xf = Xor8.build(keys)
    assert xf is not None and xf.ver == 1
    assert all(xf.may_contain(k) for k in keys[::97]), \
        "xor filters must have NO false negatives"
    miss = sum(xf.may_contain(b"absent-%d" % i) for i in range(10_000))
    assert miss < 200, f"false-positive rate blew up: {miss}/10000"


def test_read_at_protects_full_reader_set_from_lru(tmp_path, monkeypatch):
    """hummock.py read_at opened runs one at a time through _reader(), so
    the LRU cap could close an earlier reader of the SAME merge while the
    range scan still iterated it."""
    from risingwave_tpu.state import hummock
    from risingwave_tpu.state.hummock import SpillStateStore
    monkeypatch.setattr(hummock, "MAX_OPEN_READERS", 2)
    store = SpillStateStore(str(tmp_path / "d"))
    # 4 runs for one table (below the compaction threshold of 8)
    for i, epoch in enumerate(range(10, 50, 10)):
        store.ingest_batch(7, [(b"k%d%03d" % (i, j), (i, j))
                               for j in range(600)], epoch)
        store.commit_epoch(epoch)
    rows = list(store.read_at(store.committed_epoch, 7))
    assert len(rows) == 4 * 600
    store.close()


def test_completed_portal_reexecute_keeps_statement_tag():
    """pgwire/server.py:571 replied SELECT 0 to re-Execute of ANY
    completed portal; PG tags by statement kind."""
    import struct
    from risingwave_tpu.pgwire.server import PgServer
    from risingwave_tpu.sql import Database
    from tests.test_pgwire import MiniClient

    db = Database()
    db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
    server = PgServer(db).start()
    try:
        c = MiniClient(server.host, server.port)
        c.startup()

        def exec_twice(sql):
            c.send(b"P", b"\0" + sql.encode() + b"\0" + struct.pack(">H", 0))
            c.send(b"B", b"\0\0" + struct.pack(">HHH", 0, 0, 0))
            c.send(b"E", b"\0" + struct.pack(">I", 0))
            c.send(b"E", b"\0" + struct.pack(">I", 0))   # completed portal
            c.send(b"S")
            msgs = c.read_until(b"Z")
            return [b.rstrip(b"\0").decode() for t, b in msgs if t == b"C"]

        tags = exec_twice("INSERT INTO t VALUES (1, 10)")
        assert tags == ["INSERT 0 1", "INSERT 0 0"], tags
        tags = exec_twice("DELETE FROM t WHERE k = 99")
        assert tags == ["DELETE 0", "DELETE 0"], tags
        tags = exec_twice("SELECT * FROM t")
        assert tags[0].startswith("SELECT") and tags[1] == "SELECT 0", tags
    finally:
        server.stop()


@pytest.mark.slow
def test_xor8_large_run_construction_succeeds():
    """With 20-bit positions, any run big enough that seg > 2**20
    (~2.55M keys) could never peel; full-width positions build fine."""
    from risingwave_tpu.state.hummock import Xor8
    n = 2_600_000
    keys = [b"%016x" % i for i in range(n)]
    xf = Xor8.build(keys)
    assert xf is not None, "construction must not exhaust its seed retries"
    assert xf.seg > (1 << 20)
    assert all(xf.may_contain(k) for k in keys[:: n // 997])
