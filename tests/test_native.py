"""C++ native kernels: bit-parity with the Python/zlib reference paths."""
import zlib

import numpy as np
import pytest

from risingwave_tpu.core import Column, dtypes as T
from risingwave_tpu.core.encoding import encode_datum_memcomparable
from risingwave_tpu.core.vnode import compute_vnodes, vnode_of_row
from risingwave_tpu.native import (available, crc32_rows, memcmp_i64,
                                   vnodes_i64)

pytestmark = pytest.mark.skipif(not available(),
                                reason="native toolchain unavailable")


def test_crc32_rows_matches_zlib():
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(500, 13), dtype=np.uint8)
    out = crc32_rows(data)
    for i in range(0, 500, 31):
        assert out[i] == zlib.crc32(data[i].tobytes())


def test_vnodes_match_python_path():
    rng = np.random.default_rng(1)
    vals = rng.integers(-10**12, 10**12, size=2000)
    vn = compute_vnodes([Column(T.INT64, vals)])  # uses native fast path
    for i in range(0, 2000, 191):
        assert vn[i] == vnode_of_row([int(vals[i])])


def test_vnodes_fast_path_equals_slow_path():
    import risingwave_tpu.native as N
    rng = np.random.default_rng(2)
    vals = rng.integers(-2**31, 2**31, size=1000)
    col = Column(T.INT32, vals.astype(np.int32))
    fast = compute_vnodes([col])
    lib, tried = N._lib, N._tried
    try:
        N._lib, N._tried = None, True      # force numpy fallback
        slow = compute_vnodes([col])
    finally:
        N._lib, N._tried = lib, tried
    assert (fast == slow).all()


def test_memcmp_i64_matches_encoding_body():
    vals = np.array([-2**63, -5, -1, 0, 1, 7, 2**63 - 1], dtype=np.int64)
    mc = memcmp_i64(vals)
    for i, v in enumerate(vals.tolist()):
        assert mc[i].tobytes() == encode_datum_memcomparable(v, T.INT64)[1:]
    # order preservation
    keys = [mc[i].tobytes() for i in range(len(vals))]
    assert keys == sorted(keys)
