"""OverWindow frames + incremental range cache.

Reference: `src/expr/core/src/window_function/` (RowsFrame/RangeFrame),
`src/stream/src/executor/over_window/over_partition.rs` (range cache:
only affected ranges recompute), `frame_finder.rs` (affected-range
computation per frame shape).
"""
from risingwave_tpu.sql import Database
from risingwave_tpu.utils.metrics import REGISTRY


def ticks(db, n=3):
    for _ in range(n):
        db.tick()


def mk():
    db = Database()
    db.run("CREATE TABLE t (k BIGINT, ts BIGINT, v BIGINT)")
    return db


class TestRowsFrames:
    def test_moving_sum(self):
        db = mk()
        db.run("CREATE MATERIALIZED VIEW m AS SELECT k, ts, v,"
               " sum(v) OVER (PARTITION BY k ORDER BY ts"
               " ROWS BETWEEN 2 PRECEDING AND CURRENT ROW) AS s FROM t")
        db.run("INSERT INTO t VALUES (1, 1, 10), (1, 2, 20), (1, 3, 30),"
               " (1, 4, 40)")
        ticks(db)
        rows = sorted(db.query("SELECT ts, s FROM m"))
        assert rows == [(1, 10), (2, 30), (3, 60), (4, 90)]

    def test_centered_count(self):
        db = mk()
        db.run("CREATE MATERIALIZED VIEW m AS SELECT ts,"
               " count(*) OVER (PARTITION BY k ORDER BY ts"
               " ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) AS c FROM t")
        db.run("INSERT INTO t VALUES (1, 1, 0), (1, 2, 0), (1, 3, 0)")
        ticks(db)
        assert sorted(db.query("SELECT * FROM m")) == \
            [(1, 2), (2, 3), (3, 2)]

    def test_retraction_updates_frames(self):
        db = mk()
        db.run("CREATE MATERIALIZED VIEW m AS SELECT ts,"
               " sum(v) OVER (PARTITION BY k ORDER BY ts"
               " ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS s FROM t")
        db.run("INSERT INTO t VALUES (1, 1, 10), (1, 2, 20), (1, 3, 30)")
        ticks(db)
        db.run("DELETE FROM t WHERE ts = 2")
        ticks(db)
        assert sorted(db.query("SELECT * FROM m")) == \
            [(1, 10), (3, 40)]


class TestRangeFrames:
    def test_range_sum(self):
        db = mk()
        db.run("CREATE MATERIALIZED VIEW m AS SELECT ts,"
               " sum(v) OVER (PARTITION BY k ORDER BY ts"
               " RANGE BETWEEN 10 PRECEDING AND CURRENT ROW) AS s FROM t")
        # ts gaps: the value window differs from a 2-row window
        db.run("INSERT INTO t VALUES (1, 0, 1), (1, 5, 2), (1, 11, 4),"
               " (1, 40, 8)")
        ticks(db)
        rows = sorted(db.query("SELECT * FROM m"))
        # frames: [−10,0]->1; [−5,5]->3; [1,11]->6; [30,40]->8
        assert rows == [(0, 1), (5, 3), (11, 6), (40, 8)]

    def test_range_mid_insert(self):
        db = mk()
        db.run("CREATE MATERIALIZED VIEW m AS SELECT ts,"
               " sum(v) OVER (PARTITION BY k ORDER BY ts"
               " RANGE BETWEEN 10 PRECEDING AND CURRENT ROW) AS s FROM t")
        db.run("INSERT INTO t VALUES (1, 0, 1), (1, 20, 4)")
        ticks(db)
        db.run("INSERT INTO t VALUES (1, 12, 2)")   # lands inside 20's frame
        ticks(db)
        assert sorted(db.query("SELECT * FROM m")) == \
            [(0, 1), (12, 2), (20, 6)]

    def test_range_delete_updates_followers(self):
        db = mk()
        db.run("CREATE MATERIALIZED VIEW m AS SELECT ts,"
               " sum(v) OVER (PARTITION BY k ORDER BY ts"
               " RANGE BETWEEN 10 PRECEDING AND CURRENT ROW) AS s FROM t")
        db.run("INSERT INTO t VALUES (1, 0, 1), (1, 5, 2), (1, 8, 4)")
        ticks(db)
        db.run("DELETE FROM t WHERE ts = 5")
        ticks(db)
        assert sorted(db.query("SELECT * FROM m")) == [(0, 1), (8, 5)]


class TestFrameEdgeCases:
    def test_fractional_range_offset(self):
        db = Database()
        db.run("CREATE TABLE t (k BIGINT, x DOUBLE PRECISION,"
               " v DOUBLE PRECISION)")
        db.run("CREATE MATERIALIZED VIEW m AS SELECT x, sum(v) OVER"
               " (PARTITION BY k ORDER BY x RANGE BETWEEN 0.5 PRECEDING"
               " AND CURRENT ROW) AS s FROM t")
        db.run("INSERT INTO t VALUES (1, 1.0, 1), (1, 1.4, 2)")
        ticks(db)
        assert sorted(db.query("SELECT * FROM m")) == \
            [(1.0, 1.0), (1.4, 3.0)]

    def test_fractional_rows_offset_rejected(self):
        import pytest
        db = mk()
        with pytest.raises(ValueError, match="integers"):
            db.run("CREATE MATERIALIZED VIEW m AS SELECT sum(v) OVER"
                   " (ORDER BY ts ROWS BETWEEN 1.5 PRECEDING AND"
                   " CURRENT ROW) AS s FROM t")

    def test_range_offset_requires_orderable_column(self):
        import pytest
        db = Database()
        db.run("CREATE TABLE t (name VARCHAR, v BIGINT)")
        with pytest.raises(ValueError, match="numeric or datetime"):
            db.run("CREATE MATERIALIZED VIEW m AS SELECT sum(v) OVER"
                   " (ORDER BY name RANGE BETWEEN 1 PRECEDING AND"
                   " CURRENT ROW) AS s FROM t")

    def test_first_last_value_do_not_skip_nulls(self):
        db = mk()
        db.run("CREATE MATERIALIZED VIEW m AS SELECT ts,"
               " last_value(v) OVER (PARTITION BY k ORDER BY ts"
               " ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS lv,"
               " first_value(v) OVER (PARTITION BY k ORDER BY ts"
               " ROWS BETWEEN 1 PRECEDING AND CURRENT ROW) AS fv"
               " FROM t")
        db.run("INSERT INTO t VALUES (1, 1, 10), (1, 2, NULL), (1, 3, 30)")
        ticks(db)
        rows = sorted(db.query("SELECT * FROM m"))
        # lv at ts=2 is the NULL itself; fv at ts=3 is the NULL
        assert rows == [(1, 10, 10), (2, None, 10), (3, 30, None)]


class TestIncrementalRecompute:
    def test_tail_append_touches_o_delta_rows(self):
        """Appending at the order tail of a big partition must NOT
        recompute the partition (over_partition.rs range cache)."""
        db = mk()
        db.run("CREATE MATERIALIZED VIEW m AS SELECT ts,"
               " sum(v) OVER (PARTITION BY k ORDER BY ts) AS s,"
               " row_number() OVER (PARTITION BY k ORDER BY ts) AS rn"
               " FROM t")
        n = 5000
        db.run("INSERT INTO t VALUES "
               + ", ".join(f"(1, {i}, 1)" for i in range(n)))
        ticks(db)
        ctr = REGISTRY.counter("over_window_recomputed_rows", "")
        before = ctr.labels().value
        db.run("INSERT INTO t VALUES (1, 999999, 1)")   # tail append
        ticks(db)
        delta = ctr.labels().value - before
        assert delta <= 4, f"tail append recomputed {delta} rows"
        rows = dict(db.query("SELECT ts, s FROM m"))
        assert rows[999999] == n + 1

    def test_mid_insert_stays_correct(self):
        db = mk()
        db.run("CREATE MATERIALIZED VIEW m AS SELECT ts,"
               " sum(v) OVER (PARTITION BY k ORDER BY ts) AS s FROM t")
        db.run("INSERT INTO t VALUES (1, 1, 1), (1, 3, 1), (1, 5, 1)")
        ticks(db)
        db.run("INSERT INTO t VALUES (1, 2, 10)")
        ticks(db)
        assert sorted(db.query("SELECT * FROM m")) == \
            [(1, 1), (2, 11), (3, 12), (5, 13)]
