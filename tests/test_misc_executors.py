"""Changelog / Now / DynamicFilter / Sort executors (L5a inventory;
reference src/stream/src/executor/{changelog,now,dynamic_filter,sort}.rs)."""
from typing import Iterator, List

import pytest

from risingwave_tpu.core import Op, Schema, StreamChunk, dtypes as T
from risingwave_tpu.core.epoch import EpochPair, epoch_from_physical
from risingwave_tpu.ops import (ChangelogExecutor, DynamicFilterExecutor,
                                NowExecutor, SortExecutor)
from risingwave_tpu.ops.executor import Executor
from risingwave_tpu.ops.message import Barrier, Message, Watermark


class Feed(Executor):
    """Scripted message source."""

    def __init__(self, schema: Schema, msgs: List[Message]):
        super().__init__(schema, "Feed")
        self.msgs = msgs

    def execute(self) -> Iterator[Message]:
        yield from self.msgs


def bar(n: int) -> Barrier:
    return Barrier(EpochPair(epoch_from_physical(1000 + n),
                             epoch_from_physical(999 + n)))


S = Schema.of(("k", T.INT64), ("v", T.INT64))


def chunk(*op_rows):
    return StreamChunk.from_rows(S.dtypes, list(op_rows))


def test_changelog_appends_op_column():
    feed = Feed(S, [chunk((Op.INSERT, (1, 10)), (Op.DELETE, (2, 20)),
                          (Op.UPDATE_DELETE, (3, 30)),
                          (Op.UPDATE_INSERT, (3, 31))), bar(1)])
    out = [m for m in ChangelogExecutor(feed).execute()
           if isinstance(m, StreamChunk)]
    rows = [(op, r) for ch in out for op, r in ch.op_rows()]
    assert [op for op, _ in rows] == [Op.INSERT] * 4       # append-only
    # Exported codes per the reference contract (stream_chunk.rs:84):
    # Insert=1, Delete=2, UpdateDelete=4, UpdateInsert=3.
    assert [r[-1] for _, r in rows] == [1, 2, 4, 3]        # op codes
    assert ChangelogExecutor(feed).append_only


def test_now_emits_update_pairs_and_watermark():
    feed = Feed(Schema.of(), [bar(1), bar(2), bar(3)])
    msgs = list(NowExecutor(feed).execute())
    chunks = [m for m in msgs if isinstance(m, StreamChunk)]
    assert [op for op, _ in chunks[0].op_rows()] == [Op.INSERT]
    assert all([op for op, _ in c.op_rows()] ==
               [Op.UPDATE_DELETE, Op.UPDATE_INSERT] for c in chunks[1:])
    vals = [r[0] for c in chunks for _, r in c.op_rows()]
    assert vals == sorted(vals)
    wms = [m for m in msgs if isinstance(m, Watermark)]
    assert len(wms) == 3 and wms[-1].value == vals[-1]


def test_dynamic_filter_bound_movement():
    """Rows cross in/out of the output when the RHS scalar moves."""
    right_schema = Schema.of(("m", T.INT64))
    rchunk = lambda *vals: StreamChunk.from_rows(
        right_schema.dtypes, [(Op.INSERT, (v,)) for v in vals])
    left = Feed(S, [chunk((Op.INSERT, (1, 10)), (Op.INSERT, (2, 50))),
                    bar(1),
                    bar(2),
                    chunk((Op.INSERT, (3, 25))),
                    bar(3)])
    right = Feed(right_schema, [rchunk(20), bar(1),
                                rchunk(40), bar(2),
                                bar(3)])
    df = DynamicFilterExecutor(left, right, key_col=1, cmp=">")
    acc = {}
    for m in df.execute():
        if isinstance(m, StreamChunk):
            for op, r in m.op_rows():
                acc[r] = acc.get(r, 0) + op.sign
    live = sorted(r for r, n in acc.items() if n > 0)
    # bound ended at 40: only v=50 passes (25 never emitted, 10 retracted)
    assert live == [(2, 50)]


def test_dynamic_filter_retracts_on_bound_rise():
    right_schema = Schema.of(("m", T.INT64))
    left = Feed(S, [chunk((Op.INSERT, (1, 30))), bar(1), bar(2)])
    right = Feed(right_schema,
                 [StreamChunk.from_rows(right_schema.dtypes,
                                        [(Op.INSERT, (10,))]), bar(1),
                  StreamChunk.from_rows(right_schema.dtypes,
                                        [(Op.UPDATE_DELETE, (10,)),
                                         (Op.UPDATE_INSERT, (99,))]),
                  bar(2)])
    df = DynamicFilterExecutor(left, right, key_col=1, cmp=">")
    seq = [(op, r) for m in df.execute() if isinstance(m, StreamChunk)
           for op, r in m.op_rows()]
    assert seq == [(Op.INSERT, (1, 30)), (Op.DELETE, (1, 30))]


def test_dynamic_filter_rhs_delete_clears_bound():
    """Review finding: an RHS DELETE with no re-insert (empty subquery)
    must revert the bound to NULL, retracting everything."""
    right_schema = Schema.of(("m", T.INT64))
    left = Feed(S, [chunk((Op.INSERT, (1, 30))), bar(1), bar(2)])
    right = Feed(right_schema,
                 [StreamChunk.from_rows(right_schema.dtypes,
                                        [(Op.INSERT, (10,))]), bar(1),
                  StreamChunk.from_rows(right_schema.dtypes,
                                        [(Op.DELETE, (10,))]), bar(2)])
    df = DynamicFilterExecutor(left, right, key_col=1, cmp=">")
    seq = [(op, r) for m in df.execute() if isinstance(m, StreamChunk)
           for op, r in m.op_rows()]
    assert seq == [(Op.INSERT, (1, 30)), (Op.DELETE, (1, 30))]


def test_sort_forwards_other_watermarks():
    feed = Feed(S, [Watermark(0, T.INT64, 5), bar(1)])
    feed.append_only = True
    srt = SortExecutor(feed, time_col=1)
    wms = [m for m in srt.execute() if isinstance(m, Watermark)]
    assert wms and wms[0].col_idx == 0


def test_sort_releases_in_order_below_watermark():
    feed = Feed(S, [chunk((Op.INSERT, (1, 30)), (Op.INSERT, (2, 10))),
                    Watermark(1, T.INT64, 15),
                    bar(1),
                    chunk((Op.INSERT, (3, 12)), (Op.INSERT, (4, 40))),
                    Watermark(1, T.INT64, 35),
                    bar(2)])
    feed.append_only = True
    srt = SortExecutor(feed, time_col=1)
    rows = [r for m in srt.execute() if isinstance(m, StreamChunk)
            for _, r in m.op_rows()]
    # released in event-time order, only once the watermark passes
    assert rows == [(2, 10), (3, 12), (1, 30)]
