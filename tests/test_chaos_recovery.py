"""Deterministic chaos: seeded random workload with random kill/restart.

The madsim-style tier (SURVEY §4): the reference random-kills cluster roles
under a simulated network with a fixed seed and asserts streaming results
still converge. Here the single-process analog: a random DML workload against
agg/join MVs, with the Database torn down and recovered from the spill store
at random points. Every seed must converge to the batch-recompute oracle.
"""
import numpy as np
import pytest

from risingwave_tpu.sql import Database


def run_chaos(seed: int, tmpdir: str, n_rounds: int = 12) -> None:
    rng = np.random.default_rng(seed)
    db = Database(data_dir=tmpdir)
    db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
    db.run("CREATE MATERIALIZED VIEW agg AS "
           "SELECT k, count(*) AS c, sum(v) AS s FROM t GROUP BY k")
    db.run("CREATE TABLE d (k BIGINT PRIMARY KEY, name VARCHAR)")
    db.run("CREATE MATERIALIZED VIEW j AS "
           "SELECT t.k, d.name, t.v FROM t JOIN d ON t.k = d.k")
    oracle = []          # live (k, v) rows
    dim = {}
    for r in range(n_rounds):
        action = rng.random()
        if action < 0.55 or not oracle:
            n = int(rng.integers(1, 20))
            rows = [(int(rng.integers(0, 8)), int(rng.integers(-50, 50)))
                    for _ in range(n)]
            values = ", ".join(f"({k}, {v})" for k, v in rows)
            db.run(f"INSERT INTO t VALUES {values}")
            oracle += rows
        elif action < 0.75:
            k = int(rng.integers(0, 8))
            db.run(f"DELETE FROM t WHERE k = {k}")
            oracle = [r for r in oracle if r[0] != k]
        elif action < 0.85:
            k = int(rng.integers(0, 8))
            db.run(f"INSERT INTO d VALUES ({k}, 'n{k}')")
            dim[k] = f"n{k}"
        else:
            # crash: lose the process, recover from the committed epoch
            del db
            db = Database(data_dir=tmpdir)
        # invariants after every round
        agg = sorted(db.query("SELECT * FROM agg"))
        expect = {}
        for k, v in oracle:
            c, s = expect.get(k, (0, 0))
            expect[k] = (c + 1, s + v)
        assert agg == sorted((k, c, s) for k, (c, s) in expect.items()), \
            f"seed={seed} round={r}"
        j = sorted(db.query("SELECT * FROM j"))
        expect_j = sorted((k, dim[k], v) for k, v in oracle if k in dim)
        assert j == expect_j, f"seed={seed} round={r}"


@pytest.mark.parametrize("seed", [1, 7, 23])
def test_chaos_converges(seed, tmp_path):
    run_chaos(seed, str(tmp_path))
