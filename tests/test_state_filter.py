"""Run-level xor filters on the spill store (`xor_filter.rs` analog) +
the state-table point-read micro-bench.
"""
import time

from risingwave_tpu.core import dtypes as T
from risingwave_tpu.state import StateTable
from risingwave_tpu.state.hummock import SpillStateStore, Xor8
from risingwave_tpu.utils.metrics import REGISTRY


class TestXor8:
    def test_no_false_negatives_and_low_false_positives(self):
        keys = [f"k{i}".encode() for i in range(20_000)]
        xf = Xor8.build(keys)
        assert xf is not None
        assert all(xf.may_contain(k) for k in keys)       # no false negs
        fp = sum(xf.may_contain(f"absent{i}".encode())
                 for i in range(20_000))
        assert fp / 20_000 < 0.02, fp                     # ~0.39% expected

    def test_empty(self):
        xf = Xor8.build([])
        assert xf is not None


class TestStoreFilters:
    def _store(self, tmp_path, n=5000):
        store = SpillStateStore(str(tmp_path / "d"))
        t = StateTable(store, 1, [T.INT64, T.INT64], [0])
        for i in range(n):
            t.insert((i, i * 2))
        t.commit(1)
        store.commit_epoch(1)
        return store, t

    def test_negative_lookups_skip_runs(self, tmp_path):
        store, t = self._store(tmp_path)
        ctr = REGISTRY.counter("state_filter_negative_skips", "")
        before = ctr.labels().value
        for i in range(1000):
            assert t.get_by_pk((10_000_000 + i,)) is None
        skips = ctr.labels().value - before
        assert skips >= 990, skips       # xor-filter fast path took them
        # positives still found
        assert t.get_by_pk((123,)) == (123, 246)
        store.close()

    def test_point_read_microbench(self, tmp_path):
        """In-tree micro-bench (VERDICT r04 #8): prints, doesn't gate."""
        store, t = self._store(tmp_path, n=20_000)
        t0 = time.perf_counter()
        for i in range(2000):
            t.get_by_pk((i * 7 % 20_000,))
        pos = 2000 / (time.perf_counter() - t0)
        t0 = time.perf_counter()
        for i in range(2000):
            t.get_by_pk((10_000_000 + i,))
        neg = 2000 / (time.perf_counter() - t0)
        print(f"\nstate point reads: {pos:.0f} hit/s, {neg:.0f} miss/s")
        assert neg > pos * 0.5           # misses must not be slower than hits
        store.close()

    def test_pre_filter_files_still_readable(self, tmp_path):
        """Backward compat: a run footer without the filter tuple loads
        (filter=None, full read path)."""
        import os
        import pickle
        import struct
        import zlib
        from risingwave_tpu.state.hummock import BlockCache, RunReader
        path = str(tmp_path / "old.run")
        rows = [(f"k{i:04d}".encode(), (i,)) for i in range(100)]
        blob = zlib.compress(pickle.dumps(rows, protocol=4), 1)
        with open(path, "wb") as f:
            f.write(blob)
            idx = pickle.dumps(([(rows[0][0], 0, len(blob))], 100), 4)
            f.write(idx)
            f.write(struct.pack(">Q", len(blob)))
        r = RunReader("old", path, BlockCache())
        assert r.filter is None
        assert r.get(b"k0042") == (42,)
        r.close()


class TestBackupTimeTravel:
    """Backup/restore + retained-version time travel
    (`src/meta/src/backup_restore/`, `hummock/manager/time_travel.rs`)."""

    def test_backup_is_self_contained_and_immutable(self, tmp_path):
        from risingwave_tpu.sql import Database
        src = str(tmp_path / "data")
        bak = str(tmp_path / "bak")
        db = Database(data_dir=src)
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("INSERT INTO t VALUES (1, 10), (2, 20)")
        for _ in range(3):
            db.tick()
        db.store.backup(bak)
        db.run("INSERT INTO t VALUES (3, 30)")
        db.run("DELETE FROM t WHERE k = 1")
        for _ in range(3):
            db.tick()
        del db
        db2 = Database(data_dir=bak)          # restore = open the backup
        assert sorted(db2.query("SELECT * FROM t")) == [(1, 10), (2, 20)]
        del db2
        db3 = Database(data_dir=src)          # live dir unaffected
        assert sorted(db3.query("SELECT * FROM t")) == [(2, 20), (3, 30)]

    def test_time_travel_read(self, tmp_path):
        from risingwave_tpu.core import dtypes as T
        from risingwave_tpu.state import StateTable
        store = SpillStateStore(str(tmp_path / "d"))
        t = StateTable(store, 7, [T.INT64, T.INT64], [0])
        t.insert((1, 10))
        t.commit(100)
        store.commit_epoch(100)
        epoch1 = 100
        t.insert((2, 20))
        t.delete((1, 10))
        t.commit(200)
        store.commit_epoch(200)
        old = [v for _k, v in store.read_at(epoch1, 7)]
        assert old == [(1, 10)]
        new = [v for _k, v in store.read_at(10**18, 7)]
        assert new == [(2, 20)]
        import pytest
        with pytest.raises(ValueError, match="retained"):
            list(store.read_at(5, 7))
        store.close()

    def test_compaction_spares_retained_versions(self, tmp_path):
        """Files referenced only by RETAINED old manifests survive GC, so
        read_at keeps working across compaction."""
        from risingwave_tpu.core import dtypes as T
        from risingwave_tpu.state import StateTable
        store = SpillStateStore(str(tmp_path / "d"))
        t = StateTable(store, 7, [T.INT64, T.INT64], [0])
        epochs = []
        for e in range(1, 12):          # > COMPACT_THRESHOLD commits
            t.insert((e, e * 10))
            t.commit(e)
            store.commit_epoch(e)
            epochs.append(e)
        # compaction happened along the way; a retained pre-compaction
        # version must still read
        m = store.manifest_at(epochs[-2])
        assert m is not None
        rows = [v for _k, v in store.read_at(epochs[-2], 7)]
        assert len(rows) == epochs[-2]
        store.close()
