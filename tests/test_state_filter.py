"""Run-level xor filters on the spill store (`xor_filter.rs` analog) +
the state-table point-read micro-bench.
"""
import time

from risingwave_tpu.core import dtypes as T
from risingwave_tpu.state import StateTable
from risingwave_tpu.state.hummock import SpillStateStore, Xor8
from risingwave_tpu.utils.metrics import REGISTRY


class TestXor8:
    def test_no_false_negatives_and_low_false_positives(self):
        keys = [f"k{i}".encode() for i in range(20_000)]
        xf = Xor8.build(keys)
        assert xf is not None
        assert all(xf.may_contain(k) for k in keys)       # no false negs
        fp = sum(xf.may_contain(f"absent{i}".encode())
                 for i in range(20_000))
        assert fp / 20_000 < 0.02, fp                     # ~0.39% expected

    def test_empty(self):
        xf = Xor8.build([])
        assert xf is not None


class TestStoreFilters:
    def _store(self, tmp_path, n=5000):
        store = SpillStateStore(str(tmp_path / "d"))
        t = StateTable(store, 1, [T.INT64, T.INT64], [0])
        for i in range(n):
            t.insert((i, i * 2))
        t.commit(1)
        store.commit_epoch(1)
        return store, t

    def test_negative_lookups_skip_runs(self, tmp_path):
        store, t = self._store(tmp_path)
        ctr = REGISTRY.counter("state_filter_negative_skips", "")
        before = ctr.labels().value
        for i in range(1000):
            assert t.get_by_pk((10_000_000 + i,)) is None
        skips = ctr.labels().value - before
        assert skips >= 990, skips       # xor-filter fast path took them
        # positives still found
        assert t.get_by_pk((123,)) == (123, 246)
        store.close()

    def test_point_read_microbench(self, tmp_path):
        """In-tree micro-bench (VERDICT r04 #8): prints, doesn't gate."""
        store, t = self._store(tmp_path, n=20_000)
        t0 = time.perf_counter()
        for i in range(2000):
            t.get_by_pk((i * 7 % 20_000,))
        pos = 2000 / (time.perf_counter() - t0)
        t0 = time.perf_counter()
        for i in range(2000):
            t.get_by_pk((10_000_000 + i,))
        neg = 2000 / (time.perf_counter() - t0)
        print(f"\nstate point reads: {pos:.0f} hit/s, {neg:.0f} miss/s")
        assert neg > pos * 0.5           # misses must not be slower than hits
        store.close()

    def test_pre_filter_files_still_readable(self, tmp_path):
        """Backward compat: a run footer without the filter tuple loads
        (filter=None, full read path)."""
        import os
        import pickle
        import struct
        import zlib
        from risingwave_tpu.state.hummock import BlockCache, RunReader
        path = str(tmp_path / "old.run")
        rows = [(f"k{i:04d}".encode(), (i,)) for i in range(100)]
        blob = zlib.compress(pickle.dumps(rows, protocol=4), 1)
        with open(path, "wb") as f:
            f.write(blob)
            idx = pickle.dumps(([(rows[0][0], 0, len(blob))], 100), 4)
            f.write(idx)
            f.write(struct.pack(">Q", len(blob)))
        r = RunReader("old", path, BlockCache())
        assert r.filter is None
        assert r.get(b"k0042") == (42,)
        r.close()
