"""Device Nexmark generator == host connector, bit for bit.

The fused SQL pipeline's correctness story starts here: the oracle in
bench.py replays the HOST generator, so the device generator must produce
the identical stream (numeric columns exactly; strings via surrogate
decode)."""
import numpy as np
import pytest

from risingwave_tpu.connectors.nexmark import (BID_SCHEMA, AUCTION_SCHEMA,
                                               PERSON_SCHEMA,
                                               NexmarkConfig,
                                               NexmarkGenerator)
from risingwave_tpu.device.nexmark_gen import (GenCfg, SURROGATE,
                                               column_bounds, decode_column,
                                               gen_table, table_mask)

N = 5_000
SCHEMAS = {"person": PERSON_SCHEMA, "auction": AUCTION_SCHEMA,
           "bid": BID_SCHEMA}


@pytest.fixture(scope="module")
def streams():
    gen = NexmarkGenerator()
    return gen, gen.gen_range(0, N)


@pytest.mark.parametrize("table", ["person", "auction", "bid"])
def test_device_matches_host(streams, table):
    import jax.numpy as jnp
    gen, host_chunks = streams
    cfg = GenCfg.from_config(gen.cfg)
    ids = jnp.arange(N, dtype=jnp.int64)
    mask = np.asarray(table_mask(table, ids))
    cols = gen_table(cfg, table, ids)
    host = host_chunks[table]
    schema = SCHEMAS[table]
    for i, f in enumerate(schema.fields):
        dev = np.asarray(cols[f.name])[mask]
        want = host.columns[i].values
        got = decode_column(SURROGATE[table][f.name], dev)
        assert len(got) == len(want), f.name
        if want.dtype == object:
            assert all(a == b for a, b in zip(got, want)), f.name
        else:
            np.testing.assert_array_equal(got, want, err_msg=f.name)


@pytest.mark.parametrize("table", ["person", "auction", "bid"])
def test_column_bounds_hold(streams, table):
    import jax.numpy as jnp
    gen, _ = streams
    cfg = GenCfg.from_config(gen.cfg)
    ids = jnp.arange(N, dtype=jnp.int64)
    mask = np.asarray(table_mask(table, ids))
    cols = gen_table(cfg, table, ids)
    for name, arr in cols.items():
        lo, hi = column_bounds(cfg, table, name, max_events=N)
        v = np.asarray(arr)[mask]
        assert v.min() >= lo, (table, name, int(v.min()), lo)
        assert v.max() <= hi, (table, name, int(v.max()), hi)


def test_kind_proportions():
    import jax.numpy as jnp
    ids = jnp.arange(50_000, dtype=jnp.int64)
    assert int(table_mask("person", ids).sum()) == 1_000
    assert int(table_mask("auction", ids).sum()) == 3_000
    assert int(table_mask("bid", ids).sum()) == 46_000
