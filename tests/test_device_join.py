"""Device inner hash join vs a dict-based incremental-join oracle."""
import numpy as np
import pytest

import jax.numpy as jnp

from risingwave_tpu.device.join_step import DeviceHashJoin


def fold_pairs(results, state):
    """Fold emitted pair change-sets into a multiset of (jk, aval, bval)."""
    for out in results:
        n = len(out["sign"])
        for i in range(n):
            if not out["mask"][i] or out["sign"][i] == 0:
                continue
            key = (int(out["jk"][i]), int(out["a_vals"][0][i]),
                   int(out["b_vals"][0][i]))
            state[key] = state.get(key, 0) + int(out["sign"][i])
            if state[key] == 0:
                del state[key]
    return state


def oracle_join(a_rows, b_rows):
    """Full inner-join recompute over final table contents."""
    out = {}
    for jk_a, va in a_rows:
        for jk_b, vb in b_rows:
            if jk_a == jk_b:
                k = (jk_a, va, vb)
                out[k] = out.get(k, 0) + 1
    return out


def run_epochs(epochs):
    j = DeviceHashJoin([jnp.int64], [jnp.int64], capacity=8, pair_capacity=8)
    emitted = {}
    a_tbl, b_tbl = [], []
    for a_batch, b_batch in epochs:
        for jk, pk, sign, v in a_batch:
            j.push_rows("a", [jk], [pk], [sign], [[v]])
            if sign > 0:
                a_tbl.append(((jk), v))
            else:
                a_tbl.remove((jk, v))
        for jk, pk, sign, v in b_batch:
            j.push_rows("b", [jk], [pk], [sign], [[v]])
            if sign > 0:
                b_tbl.append((jk, v))
            else:
                b_tbl.remove((jk, v))
        o1, o2 = j.flush_epoch()
        fold_pairs([o1, o2], emitted)
    return emitted, oracle_join(a_tbl, b_tbl)


def test_basic_insert_matching():
    emitted, want = run_epochs([
        ([(1, 100, 1, 10), (2, 101, 1, 20)], [(1, 200, 1, 77)]),
        ([(1, 102, 1, 11)], [(2, 201, 1, 88), (1, 202, 1, 99)]),
    ])
    assert emitted == want and len(want) > 0


def test_delete_retracts_pairs():
    emitted, want = run_epochs([
        ([(1, 100, 1, 10)], [(1, 200, 1, 77), (1, 201, 1, 78)]),
        ([(1, 100, -1, 10)], []),          # delete the left row
    ])
    assert want == {} and emitted == {}


def test_same_epoch_both_sides_no_double_count():
    # dA><B_old + A_new><dB must count the (dA, dB) pair exactly once
    emitted, want = run_epochs([
        ([(5, 1, 1, 50)], [(5, 2, 1, 60)]),
    ])
    assert emitted == want == {(5, 50, 60): 1}


def test_randomized_vs_oracle():
    rng = np.random.default_rng(3)
    j = DeviceHashJoin([jnp.int64], [jnp.int64], capacity=8, pair_capacity=8)
    emitted = {}
    tables = {"a": {}, "b": {}}
    next_pk = [0]
    for _ in range(8):
        for side in ("a", "b"):
            n = 40
            jks, pks, signs, vs = [], [], [], []
            for _ in range(n):
                if tables[side] and rng.random() < 0.3:
                    pk = list(tables[side])[int(rng.integers(
                        0, len(tables[side])))]
                    if pk in pks:
                        continue  # one delta per pk per epoch in this test
                    jk, v = tables[side].pop(pk)
                    jks.append(jk); pks.append(pk); signs.append(-1)
                    vs.append(v)
                else:
                    jk = int(rng.integers(0, 12))
                    v = int(rng.integers(0, 1000))
                    pk = next_pk[0]; next_pk[0] += 1
                    tables[side][pk] = (jk, v)
                    jks.append(jk); pks.append(pk); signs.append(1)
                    vs.append(v)
            j.push_rows(side, jks, pks, signs, [vs])
        o1, o2 = j.flush_epoch()
        fold_pairs([o1, o2], emitted)
    want = oracle_join([v for v in tables["a"].values()],
                       [v for v in tables["b"].values()])
    assert emitted == want
    assert int(j.a.count) == len(tables["a"])
    assert int(j.b.count) == len(tables["b"])
