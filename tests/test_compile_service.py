"""ISSUE 6 — AOT compile service: bucketed shapes, background AOT, and
zero-compile warm starts.

Contracts under test:
 * bit-identical MV results across a bucket-boundary growth with the
   service on (the interpreted bridge and the compiled executables are
   the same computation);
 * executable swap mid-job at a barrier: epochs served on the
   interpreted path while compiles are pending, compiled dispatch after
   they land, results unchanged across the swap;
 * zero-compile DROP + re-CREATE (and second identically-shaped job),
   asserted via profiler compile counts AND the service's fresh-compile
   counter;
 * the plan-shape hash keys the high-water presize registry, so a
   re-created plan presizes under ANY name (satellite of PR 4's
   index+type keying);
 * the per-epoch-bounded capacity model: `touched`/pair-buffer needs get
   flat headroom, never horizon extrapolation;
 * `risectl compile-status` reports pending/ready/cached per signature.
"""
import json
import time

import pytest

from risingwave_tpu.config import DeviceConfig
from risingwave_tpu.device.capacity import (EPOCH_HEADROOM, bucket, ladder,
                                            project, project_epoch)
from risingwave_tpu.sql import Database

N = 5_000
CHUNK = 32          # fused epoch = 64 * CHUNK = 2048 events

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           " nexmark.table='bid', nexmark.max.events='{n}',"
           " nexmark.chunk.size='{c}')")
Q4 = ("CREATE MATERIALIZED VIEW {name} AS SELECT auction, count(*) AS c,"
      " sum(price) AS s, max(price) AS m FROM bid GROUP BY auction")


def drive(db, n=N, chunk=CHUNK):
    for _ in range(n // (64 * chunk) + 3):
        db.tick()


def _svc():
    from risingwave_tpu.device.compile_service import get_service
    return get_service()


@pytest.fixture(scope="module")
def oracle():
    db = Database(device="off")
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4.format(name="q4"))
    drive(db)
    return sorted(db.query("SELECT * FROM q4"))


# ---------------------------------------------------------------------------
# bucket ladder + per-epoch capacity model (pure math)
# ---------------------------------------------------------------------------


def test_ladder_rungs():
    # every rung pow2, strictly above current, topped by bucket(predicted)
    r = ladder(64, 5_000)
    assert r and all(c & (c - 1) == 0 for c in r)
    assert all(c > 64 for c in r)
    assert r[-1] == bucket(5_000, lo=1)
    assert r == sorted(r)
    # capped at `rungs`, keeping the first step and the top
    r = ladder(64, 1 << 20, rungs=3)
    assert len(r) == 3
    assert r[0] == 128 and r[-1] == 1 << 20
    # nothing to pre-compile when the prediction fits the current bucket
    assert ladder(1024, 900) == []
    assert ladder(1024, 1024) == []


def test_project_epoch_flat_headroom():
    assert project_epoch(0) == 0
    assert project_epoch(1000) == int(1000 * EPOCH_HEADROOM)
    # and NEVER scales with any horizon — unlike project()
    assert project_epoch(1000) < project(1000, 2_048, 10_000_000)


def test_node_level_need_split():
    """JoinNode pair buffers and agg `touched` are per-epoch-bounded;
    join sides and live groups are cumulative."""
    import jax.numpy as jnp
    from risingwave_tpu.device.fused import JoinNode, PackPlan
    pack = PackPlan.plan([(0, 1000, 1)])
    node = JoinNode(0, 1, [0], [0], pack, None, 256, 1024,
                    [jnp.int64], [jnp.int64])
    stats = {"need_a": 10, "need_b": 20, "need_pairs": 999,
             "packbad": 0, "rows_in": 0, "rows_out": 0}
    assert node.cap_needs(stats) == {"a": 10, "b": 20, "pairs": 999}
    assert node.cap_needs_cum(stats) == {"a": 10, "b": 20}
    assert node.cap_needs_epoch(stats) == {"pairs": 999}


def test_per_epoch_slot_not_horizon_inflated():
    """The predictor must size a `touched`-dominated agg from flat
    headroom, not extrapolate it over the event horizon (the window-query
    overshoot carried from PR 4)."""
    from risingwave_tpu.device.fused import AggNode
    db = Database(device=DeviceConfig(capacity=64, aot_compile=False))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4.format(name="q4"))
    job = db._fused["q4"]
    job.counter = 2_048
    job.max_events = 10_000_000          # long horizon: inflation territory
    agg_i = next(i for i, n in enumerate(job.program.nodes)
                 if isinstance(n, AggNode))
    # few live groups (cumulative=8), one epoch touched 1000 dying groups
    needs = {agg_i: {"main": 1_000}}
    cum = {agg_i: {"main": 8}}
    epoch = {agg_i: {"main": 1_000}}
    target = job._predict_caps(needs, cum, epoch)[agg_i]["main"]
    inflated = bucket(project(1_000, 2_048, 10_000_000))
    assert target >= 1_000                      # correctness floor
    assert target < inflated / 8, (
        f"per-epoch `touched` was horizon-extrapolated: {target} "
        f"(old model: {inflated})")
    # legacy call shape (no split views) keeps the old extrapolation
    legacy = job._predict_caps(needs)[agg_i]["main"]
    assert legacy == inflated


# ---------------------------------------------------------------------------
# plan-shape hash
# ---------------------------------------------------------------------------


def test_plan_shape_hash_stable_across_instances():
    """Two Databases planning the same SQL produce the same plan-shape
    hash and node shape keys; a different query differs."""
    hashes, keysets = [], []
    for _ in range(2):
        db = Database(device=DeviceConfig(aot_compile=False))
        db.run(BID_SRC.format(n=N, c=CHUNK))
        db.run(Q4.format(name="q4"))
        from risingwave_tpu.device.fused import node_shape_key
        job = db._fused["q4"]
        hashes.append(job.plan_hash)
        keysets.append(sorted(node_shape_key(n)
                              for n in job.program.nodes))
    assert hashes[0] == hashes[1]
    assert keysets[0] == keysets[1]
    db = Database(device=DeviceConfig(aot_compile=False))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run("CREATE MATERIALIZED VIEW q4 AS SELECT bidder, count(*) AS c"
           " FROM bid GROUP BY bidder")
    assert db._fused["q4"].plan_hash not in hashes


# ---------------------------------------------------------------------------
# background AOT: interpreted bridge, swap at a barrier, bucket growth
# ---------------------------------------------------------------------------


@pytest.mark.aot
def test_interpreted_bridge_then_swap_bit_identical():
    """With every background compile HELD, the job must come online and
    serve correct epochs on the interpreted path; after the hold lifts,
    compiled executables swap in at the next barrier and the final MV is
    bit-identical to the host path — across a bucket-boundary growth
    (capacity=64 forces at least one). Uses a max.events no other test
    shares: the executable cache is process-global, and a plan another
    test already compiled would be READY despite the hold."""
    import threading
    n = N + 192
    host = Database(device="off")
    host.run(BID_SRC.format(n=n, c=CHUNK))
    host.run(Q4.format(name="q4"))
    drive(host, n=n)
    oracle = sorted(host.query("SELECT * FROM q4"))
    svc = _svc()
    hold = threading.Event()
    svc.hold = hold
    try:
        db = Database(device=DeviceConfig(capacity=64, aot_compile=True))
        db.run(BID_SRC.format(n=n, c=CHUNK))
        db.run(Q4.format(name="q4"))
        job = db._fused["q4"]
        assert job.compile_service is svc
        eager0 = svc.eager_steps
        db.tick()
        assert svc.eager_steps > eager0, \
            "held compiles must serve epochs on the interpreted bridge"
        # mid-bridge queries are served (sync + pull works eagerly);
        # only ONE tick before this so the bounded source still has
        # epochs left for the post-swap drive below
        assert db.query("SELECT count(*) FROM q4")
    finally:
        svc.hold = None
        hold.set()
    assert svc.wait_idle(120), "background compiles must land"
    compiled0 = svc.compiled_steps
    drive(db, n=n)                 # swap happened at a barrier boundary
    assert svc.compiled_steps > compiled0, \
        "ready executables must take over dispatch after the swap"
    assert job.growth_replays >= 1, "test must cross a bucket boundary"
    assert sorted(db.query("SELECT * FROM q4")) == oracle


@pytest.mark.aot
def test_compile_events_labeled():
    """Service compiles land in the requesting job's profiler with
    `aot`/`bucket` labels and the idx:Type:sighash label grammar. Uses a
    plan shape no other test compiles (distinct max.events changes the
    source signature) so fresh events are guaranteed despite the shared
    process-global executable cache."""
    n = N - 64
    db = Database(device=DeviceConfig(capacity=64, aot_compile=True))
    db.run(BID_SRC.format(n=n, c=CHUNK))
    db.run(Q4.format(name="q4"))
    drive(db, n=n)
    assert _svc().wait_idle(120)
    job = db._fused["q4"]
    evs = [r for r in job.profiler.compile_info]
    assert evs, "AOT compiles must be recorded in the profiler"
    for rec in evs:
        assert rec["aot"] is True
        idx, tname, sig = rec["label"].split(":")
        assert tname.endswith("Node") and len(sig) == 8
        assert "bucket" in rec
    assert db.query("SELECT * FROM q4")


# ---------------------------------------------------------------------------
# zero-compile warm starts
# ---------------------------------------------------------------------------


@pytest.mark.aot
def test_zero_compile_drop_recreate(oracle):
    """DROP + re-CREATE of the same plan performs ZERO fresh compiles
    (service cache keyed on structural signatures) and zero growth
    replays (presize registry keyed on the plan-shape hash).

    compile_buckets=0 pins the count to DISPATCH-shaped compiles: the
    predicted-bucket pre-warm (exercised elsewhere) schedules shapes
    from stats snapshots whose sync timing differs between the first
    and second incarnation, which would make the fresh-compile counter
    nondeterministic."""
    svc = _svc()
    db = Database(device=DeviceConfig(capacity=64, aot_compile=True,
                                      compile_buckets=0))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4.format(name="q4"))
    drive(db)
    assert db._fused["q4"].growth_replays >= 1
    assert svc.wait_idle(120)
    db.run("DROP MATERIALIZED VIEW q4")
    fresh0 = svc.compiles_done + svc.compiles_failed
    db.run(Q4.format(name="q4"))
    job2 = db._fused["q4"]
    drive(db)
    assert svc.wait_idle(120)
    assert svc.compiles_done + svc.compiles_failed == fresh0, \
        "re-CREATE of an identical plan must not compile anything"
    assert len(job2.profiler.compiles) == 0, \
        "zero compile events for the re-created job"
    assert job2.growth_replays == 0, \
        "plan-hash presize registry must absorb the growth ladder"
    assert sorted(db.query("SELECT * FROM q4")) == oracle


@pytest.mark.aot
def test_zero_compile_identically_shaped_second_job(oracle):
    """A SECOND job with the same plan shape — different name, first one
    still running — dispatches entirely from the shared executable
    cache: zero fresh compiles, `cached` in compile-status.
    (compile_buckets=0 for the same determinism reason as the
    drop/re-create test.)"""
    svc = _svc()
    db = Database(device=DeviceConfig(capacity=64, aot_compile=True,
                                      compile_buckets=0))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4.format(name="q4"))
    drive(db)
    assert svc.wait_idle(120)
    fresh0 = svc.compiles_done + svc.compiles_failed
    db.run(Q4.format(name="q4_twin"))
    twin = db._fused["q4_twin"]
    assert twin.plan_hash == db._fused["q4"].plan_hash
    drive(db)
    assert svc.wait_idle(120)
    assert svc.compiles_done + svc.compiles_failed == fresh0
    assert len(twin.profiler.compiles) == 0
    assert sorted(db.query("SELECT * FROM q4_twin")) == oracle
    states = {r["state"] for r in svc.status("q4_twin")}
    assert states and states <= {"cached"}, states


@pytest.mark.aot
def test_registry_presize_survives_rename(oracle):
    """The high-water presize registry keys on the PLAN-SHAPE hash, not
    the job name: a re-created identical plan under a new name starts at
    the predecessor's capacities."""
    db = Database(device=DeviceConfig(capacity=64, aot_compile=True))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4.format(name="q4"))
    drive(db)
    job1 = db._fused["q4"]
    assert job1.growth_replays >= 1
    hints = job1.shape_hints()
    db.run("DROP MATERIALIZED VIEW q4")
    db.run(Q4.format(name="renamed"))
    job2 = db._fused["renamed"]
    assert job2.plan_hash == job1.plan_hash
    got = job2.shape_hints()
    for k, caps in hints.items():
        for s, c in caps.items():
            assert got[k][s] >= c, (k, s)
    drive(db)
    assert job2.growth_replays == 0
    assert sorted(db.query("SELECT * FROM renamed")) == oracle


def test_different_plan_never_inherits():
    """A different query under a recycled name gets neither presize
    hints nor executables (plan hash + structural keys differ)."""
    db = Database(device=DeviceConfig(capacity=64, aot_compile=True))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4.format(name="q4"))
    drive(db)
    db.run("DROP MATERIALIZED VIEW q4")
    db.run("CREATE MATERIALIZED VIEW q4 AS SELECT bidder, count(*) AS c"
           " FROM bid GROUP BY bidder")
    for node in db._fused["q4"].program.nodes:
        for cap in node.cap_current().values():
            assert cap <= 4 * 64, "stale hint presized a different plan"


# ---------------------------------------------------------------------------
# surfaces: compile-status ctl + service summary
# ---------------------------------------------------------------------------


@pytest.mark.aot
def test_ctl_compile_status(tmp_path, capsys, oracle):
    from risingwave_tpu import ctl
    d = str(tmp_path / "data")
    db = Database(data_dir=d, device=DeviceConfig(capacity=64,
                                                  aot_compile=True))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4.format(name="q4"))
    drive(db)
    assert _svc().wait_idle(120)
    db.store.close()
    del db
    assert ctl.main(["compile-status", "q4", "--data-dir", d,
                     "--wait", "120"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert set(out) == {"q4"}
    rep = out["q4"]
    assert rep["aot"] is True and rep["plan_hash"]
    assert rep["signatures"], "per-signature rows must be reported"
    states = {r["state"] for r in rep["signatures"]}
    assert states <= {"ready", "cached"}, states
    assert rep["counts"]["pending"] == 0
    # unknown job: explicit failure
    with pytest.raises(SystemExit):
        ctl.main(["compile-status", "nope", "--data-dir", d])
    capsys.readouterr()


@pytest.mark.aot
def test_service_summary_counters():
    svc = _svc()
    s = svc.summary()
    assert set(s) >= {"compiles", "failed", "cache_hits", "pending",
                      "eager_steps", "compiled_steps"}
    assert s["failed"] == 0, \
        f"background AOT compiles failed during this suite: {svc.status()}"


def test_aot_off_restores_inline_compiles(oracle):
    """DeviceConfig.aot_compile=False keeps the pre-ISSUE-6 lifecycle:
    no service attached, inline compile events on the epoch loop."""
    db = Database(device=DeviceConfig(capacity=64, aot_compile=False))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4.format(name="q4"))
    job = db._fused["q4"]
    assert job.compile_service is None
    assert job.program.compile_service is None
    drive(db)
    assert sorted(db.query("SELECT * FROM q4")) == oracle
    assert job.profiler.compiles, "inline path must record its compiles"
