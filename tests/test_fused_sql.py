"""Fused device path behind SQL: whole-fragment epoch programs.

Every test compares the fused MV (device='on', single chip — CPU platform
here) against the SAME SQL run on the host path (device off), which is
itself oracle-tested elsewhere — plus a direct numpy oracle for q4.
"""
import numpy as np
import pytest

from risingwave_tpu.config import DeviceConfig
from risingwave_tpu.sql import Database

N = 5_000
CHUNK = 32          # fused epoch = 64 * CHUNK = 2048 events

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           " nexmark.table='bid', nexmark.max.events='{n}',"
           " nexmark.chunk.size='{c}')")
Q4 = ("CREATE MATERIALIZED VIEW q4 AS SELECT auction, count(*) AS c,"
      " sum(price) AS s, max(price) AS m FROM bid GROUP BY auction")


def drive(db, n=N, chunk=CHUNK):
    for _ in range(n // (64 * chunk) + 3):
        db.tick()


def mk(device):
    return Database(device=DeviceConfig(capacity=512) if device else "off")


def host_rows(sql_src, sql_mv, mv, n=N, chunk=CHUNK):
    db = mk(False)
    db.run(sql_src)
    db.run(sql_mv)
    drive(db, n, chunk)
    return db.query(f"SELECT * FROM {mv}")


def test_q4_fused_matches_host_and_oracle():
    db = mk(True)
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    obj = db.catalog.get("q4")
    assert (obj.runtime or {}).get("fused_job") is not None, \
        "q4 plan must fuse"
    assert "bid" not in db._iters, "virtual source must not run on host"
    drive(db)
    got = sorted(db.query("SELECT * FROM q4"))
    want = sorted(host_rows(BID_SRC.format(n=N, c=CHUNK), Q4, "q4"))
    assert got == want
    # independent numpy oracle over the host generator's stream
    from risingwave_tpu.connectors.nexmark import NexmarkGenerator
    ch = NexmarkGenerator().gen_range(0, N)["bid"]
    auction = ch.columns[0].values.astype(np.int64)
    price = ch.columns[2].values.astype(np.int64)
    order = np.argsort(auction, kind="stable")
    k = auction[order]
    bounds = np.flatnonzero(np.r_[True, k[1:] != k[:-1]])
    cnt = np.diff(np.r_[bounds, len(k)])
    s = np.add.reduceat(price[order], bounds)
    m = np.maximum.reduceat(price[order], bounds)
    oracle = {int(a): (int(c), int(sv), int(mv))
              for a, c, sv, mv in zip(k[bounds], cnt, s, m)}
    assert len(got) == len(oracle)
    for a, c, sv, mv in got:
        assert oracle[int(a)] == (int(c), int(sv), int(mv))


def test_q4_fused_capacity_growth_replay():
    """Start with a tiny capacity: the job must detect overflow at sync,
    grow, and deterministically replay — same answer."""
    db = Database(device=DeviceConfig(capacity=64))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    drive(db)
    got = sorted(db.query("SELECT * FROM q4"))
    want = sorted(host_rows(BID_SRC.format(n=N, c=CHUNK), Q4, "q4"))
    assert got == want


def test_fused_recovery_replays_to_committed(tmp_path):
    d = str(tmp_path / "data")
    db = Database(data_dir=d, device=DeviceConfig(capacity=512))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    drive(db)
    want = sorted(db.query("SELECT * FROM q4"))
    committed = db._fused["q4"].committed
    assert committed >= N
    del db
    db2 = Database(data_dir=d, device=DeviceConfig(capacity=512))
    job = db2._fused["q4"]
    assert job.committed == committed
    assert sorted(db2.query("SELECT * FROM q4")) == want


def test_unfusable_plan_falls_back_and_activates_source():
    """avg() has no fused lowering -> host path; the virtual source must
    activate so the host DAG gets events."""
    db = mk(True)
    db.run(BID_SRC.format(n=2000, c=CHUNK))
    db.run("CREATE MATERIALIZED VIEW q4a AS SELECT auction, avg(bidder) "
           "AS b FROM bid GROUP BY auction")
    obj = db.catalog.get("q4a")
    assert (obj.runtime or {}).get("fused_job") is None
    assert "bid" in db._iters          # activated
    drive(db, 2000)
    got = sorted(db.query("SELECT * FROM q4a"))
    want = sorted(host_rows(
        BID_SRC.format(n=2000, c=CHUNK),
        "CREATE MATERIALIZED VIEW q4a AS SELECT auction, avg(bidder) "
        "AS b FROM bid GROUP BY auction", "q4a", 2000))
    assert got == want


def test_join_pair_capacity_growth_replay():
    """Regression (r03): JoinNode.grow mutates the pair capacity `m`, a
    jit-static trace parameter — jax's dispatch fast path keys static
    arguments by object identity, so without the _mut_sig salt the grown
    join silently reused the executable traced with the old m and dropped
    pairs. Tiny capacities force the full grow->replay cascade."""
    q7ish = ("CREATE MATERIALIZED VIEW j AS "
             "SELECT AB.auction, AB.num FROM ("
             "  SELECT bid.auction, count(*) AS num, window_start AS ws"
             "  FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)"
             "  GROUP BY window_start, bid.auction) AB JOIN ("
             "  SELECT max(CB.num) AS maxn, CB.ws AS wsc FROM ("
             "    SELECT count(*) AS num, window_start AS ws"
             "    FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND)"
             "    GROUP BY bid.auction, window_start) CB GROUP BY CB.ws"
             ") MB ON AB.ws = MB.wsc AND AB.num >= MB.maxn")
    dev = Database(device=DeviceConfig(capacity=16))
    dev.run(BID_SRC.format(n=N, c=CHUNK))
    dev.run(q7ish)
    job = dev._fused.get("j")
    assert job is not None
    m0 = next(n.m for n in job.program.nodes
              if type(n).__name__ == "JoinNode")
    drive(dev)
    m1 = next(n.m for n in job.program.nodes
              if type(n).__name__ == "JoinNode")
    assert m1 > m0, "test must exercise pair-capacity growth"
    got = sorted(dev.query("SELECT * FROM j"))
    want = sorted(host_rows(BID_SRC.format(n=N, c=CHUNK), q7ish, "j"))
    assert got == want and len(got) > 0
