"""Fault-tolerance v3 chaos suite.

The four tentpoles of the v3 round, each asserted end to end and
ledger-replayable (seeded failpoints / deterministic poison content):

* **In-place fused-job recovery** — a device-path failure mid-epoch
  (armed `fused.*` failpoint) heals WITHOUT a DDL-replay restart: state
  rebuilds from the last checkpoint, the crash-window epochs re-dispatch
  from the coordinator-side epoch event log, and the whole recovery runs
  on AOT-cached executables (zero fresh compiles), bit-identical.
* **Poison-pill quarantine** — a deterministic poison chunk that
  re-kills every respawn is sidelined into the durable `rw_dead_letter`
  table after bounded respawns; the job keeps making progress, and
  `risectl dlq` lists/requeues the sidelined rows.
* **Coordinated multi-failure respawn** — two simultaneous worker kills
  (both workers of one set; one worker each of a join set AND its
  downstream agg set) converge in place, bit-identical, zero
  escalations.
* **Durable sink journal** — across a coordinator kill + restart, a
  post-respawn (v1 full) refresh delivers ZERO duplicate rows to a file
  sink: the per-pk delivered mirror is rebuilt from the journaled sink
  log.
"""
import json
import os

import pytest

from risingwave_tpu.config import ROBUSTNESS, DeviceConfig
from risingwave_tpu.sql import Database
from risingwave_tpu.sql.database import _walk_executors
from risingwave_tpu.utils import failpoint as fp

pytestmark = pytest.mark.chaos

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           " nexmark.table='bid', nexmark.max.events='{n}',"
           " nexmark.chunk.size='{c}')")
Q1_MV = ("CREATE MATERIALIZED VIEW q1a AS SELECT bidder, count(*) AS n,"
         " sum(price) AS dol, max(price) AS top FROM bid GROUP BY bidder")
AUCTION_SRC = ("CREATE SOURCE auction (id BIGINT, item_name VARCHAR,"
               " description VARCHAR, initial_bid BIGINT, reserve BIGINT,"
               " date_time TIMESTAMP, expires TIMESTAMP, seller BIGINT,"
               " category BIGINT, extra VARCHAR) WITH (connector='nexmark',"
               " nexmark.table='auction', nexmark.max.events='{n}',"
               " nexmark.chunk.size='{c}')")
PERSON_SRC = ("CREATE SOURCE person (id BIGINT, name VARCHAR,"
              " email_address VARCHAR, credit_card VARCHAR, city VARCHAR,"
              " state VARCHAR, date_time TIMESTAMP, extra VARCHAR)"
              " WITH (connector='nexmark', nexmark.table='person',"
              " nexmark.max.events='{n}', nexmark.chunk.size='{c}')")


@pytest.fixture(autouse=True)
def _fast_and_clean():
    saved = (ROBUSTNESS.respawn_backoff_s, ROBUSTNESS.spawn_backoff_s,
             ROBUSTNESS.poison_threshold, ROBUSTNESS.incremental_refresh,
             ROBUSTNESS.fused_recovery_attempts)
    ROBUSTNESS.respawn_backoff_s = 0.001
    ROBUSTNESS.spawn_backoff_s = 0.001
    fp.reset()
    yield
    fp.reset()
    (ROBUSTNESS.respawn_backoff_s, ROBUSTNESS.spawn_backoff_s,
     ROBUSTNESS.poison_threshold, ROBUSTNESS.incremental_refresh,
     ROBUSTNESS.fused_recovery_attempts) = saved


def remotes_of(db, name):
    out = []
    shared = db.catalog.get(name).runtime["shared"]
    for e in _walk_executors(shared.upstream):
        r = getattr(e, "_remote", None)
        if r is not None:
            out.append(r)
    return out


def kill_on_next_chunk(rset, victim, side=0):
    """Hook the victim's input channel: hard-kill the worker right after
    its next dispatched data chunk (mid-epoch, deterministic by
    construction)."""
    from risingwave_tpu.core.chunk import StreamChunk
    vin = rset.in_channels[side][victim]
    orig = vin.send

    def send_and_kill(msg):
        orig(msg)
        if isinstance(msg, StreamChunk):
            vin.send = orig
            rset.workers[victim].proc.kill()
            rset.workers[victim].proc.wait()
    vin.send = send_and_kill


# ---------------------------------------------------------------------------
# tentpole 1: in-place fused-job recovery (no DDL replay, bit-identical)
# ---------------------------------------------------------------------------

N, CHUNK = 4096, 32                     # fused epoch cadence = 2048
TICKS = N // 2048 + 3


def _fused_q1(arm=None, capacity=512, aot=False, buckets=4):
    db = Database(device=DeviceConfig(capacity=capacity, aot_compile=aot,
                                      compile_buckets=buckets))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q1_MV)
    job = db.catalog.get("q1a").runtime["fused_job"]
    assert job is not None
    if arm:
        fp.arm(*arm)
    for _ in range(TICKS):
        db.tick()
    rows = db.query("SELECT * FROM q1a")
    fp.reset()
    return rows, job, db


@pytest.mark.parametrize("point", ["fused.dispatch", "fused.device_sync",
                                   "fused.checkpoint_commit"])
def test_fused_device_fault_recovers_in_place_bit_identical(point):
    """A fused.* failpoint mid-run (fires once, seeded — the
    ledger-replayable arming style) must heal IN PLACE: same job object
    (no DDL replay), exactly one recovery, and the final MV bit-identical
    to an undisturbed run."""
    want, j0, _ = _fused_q1()
    assert j0.recoveries == 0
    got, job, db = _fused_q1(arm=(point, 1.0, 0, 1))
    assert job.recoveries == 1, point
    assert db._fused["q1a"] is job, "in-place recovery must not rebuild"
    assert got == want, point
    from risingwave_tpu.utils.metrics import REGISTRY
    assert "fused_recoveries_total" in REGISTRY.expose()


def test_fused_growth_replay_fault_recovers():
    """A fault during a capacity growth replay (tiny capacity forces
    growth) recovers in place at the GROWN capacities and still matches
    the undisturbed run."""
    want, _, _ = _fused_q1(capacity=4)
    got, job, _ = _fused_q1(arm=("fused.growth_replay", 1.0, 0, 1),
                            capacity=4)
    assert job.recoveries >= 1
    assert job.growth_replays >= 1
    assert got == want


@pytest.mark.aot
def test_fused_recovery_is_zero_compile():
    """The whole in-place recovery (history rebuild + crash-window
    re-dispatch) runs on the AOT-cached executables: the compile
    service's compile count must not move across the recovery.
    compile_buckets=0 pins bucket pre-warm off so the only possible
    compiles WOULD be recovery-induced retraces."""
    from risingwave_tpu.device.compile_service import get_service
    n2 = 8192                            # 4 epochs: warm 2, fault mid-run
    db = Database(device=DeviceConfig(capacity=512, aot_compile=True,
                                      compile_buckets=0))
    db.run(BID_SRC.format(n=n2, c=CHUNK))
    db.run(Q1_MV)
    job = db.catalog.get("q1a").runtime["fused_job"]
    db.tick()
    db.tick()
    assert not job.drained
    svc = get_service()
    assert svc.wait_idle(120.0)
    before = svc.summary()["compiles"]
    fp.arm("fused.dispatch", 1.0, 0, 1)
    try:
        for _ in range(n2 // 2048 + 3):
            db.tick()
        job.sync()
    finally:
        fp.reset()
    assert job.recoveries == 1
    assert svc.wait_idle(120.0)
    assert svc.summary()["compiles"] == before, \
        "in-place fused recovery must be zero-compile"
    # bit-identity vs an undisturbed run of the same stream
    db2 = Database(device=DeviceConfig(capacity=512))
    db2.run(BID_SRC.format(n=n2, c=CHUNK))
    db2.run(Q1_MV)
    for _ in range(n2 // 2048 + 3):
        db2.tick()
    assert db.query("SELECT * FROM q1a") == db2.query(
        "SELECT * FROM q1a")


def test_fused_recovery_attempts_bound_escalates():
    """Past RW_FUSED_RECOVERY_ATTEMPTS the original device fault
    propagates (the classic DDL-replay restart path owns it) instead of
    looping forever."""
    from risingwave_tpu.utils.failpoint import FailpointError
    ROBUSTNESS.fused_recovery_attempts = 2
    with pytest.raises(FailpointError):
        _fused_q1(arm=("fused.dispatch", 1.0, 0, None))


# ---------------------------------------------------------------------------
# tentpole 2: poison-pill quarantine + rw_dead_letter + risectl dlq
# ---------------------------------------------------------------------------


def _agg_db(data_dir=None):
    db = Database(data_dir=data_dir)
    db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement = 'process'")
    db.run("SET streaming_supervision TO true")
    db.run("CREATE MATERIALIZED VIEW ra AS SELECT k, count(*) AS c,"
           " sum(v) AS s FROM t GROUP BY k")
    return db


def test_poison_chunk_quarantined_job_progresses(tmp_path, monkeypatch):
    """A deterministic poison row (RW_POISON_PILL content trigger —
    every respawn inherits it and re-dies on the same replayed window)
    is sidelined into rw_dead_letter after RW_POISON_THRESHOLD respawns;
    the job keeps making progress past it, `risectl dlq` lists it, and
    after the operator fixes the poison condition a requeue re-delivers
    the sidelined rows exactly once."""
    monkeypatch.setenv("RW_POISON_PILL", "1:666")   # v == 666 kills
    d = str(tmp_path / "data")
    db = _agg_db(data_dir=d)
    rset = remotes_of(db, "ra")[0]
    db.run("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
    for _ in range(4):
        db.tick()
    db.run("INSERT INTO t VALUES (4, 666), (5, 50)")
    for _ in range(8):
        db.tick()
    # the job made progress PAST the poison: the healthy row landed,
    # the poisoned window is gone from the dataflow
    got = sorted(db.query("SELECT * FROM ra"))
    keys = [r[0] for r in got]
    assert 5 in keys and 4 not in keys
    assert rset.supervisor.quarantined >= 1
    assert rset.supervisor._escalated is None
    # audit trail: rw_dead_letter holds the sidelined rows, durable
    dl = db.query("SELECT * FROM rw_dead_letter")
    assert any(r[1] == "ra" and r[8] == "quarantined" and "666" in r[7]
               for r in dl)
    from risingwave_tpu.utils.metrics import REGISTRY
    assert 'supervisor_quarantined_total{job="ra"}' in REGISTRY.expose()
    # risectl dlq lists the same rows straight off the durable table
    from risingwave_tpu import ctl
    import io
    import contextlib
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = ctl.main(["dlq", "ra", "--data-dir", d])
    assert rc == 0 and "666" in buf.getvalue() \
        and "quarantined" in buf.getvalue()
    # operator fixes the poison condition, requeues: rows re-deliver
    monkeypatch.delenv("RW_POISON_PILL")
    n = db.dlq_requeue("ra")
    assert n >= 1
    for _ in range(6):
        db.tick()
    got = sorted(db.query("SELECT * FROM ra"))
    want = sorted(db.query("SELECT k, count(*), sum(v) FROM t GROUP BY k"))
    assert got == want and any(r[0] == 4 for r in got)
    assert all(r[8] == "requeued"
               for r in db.query("SELECT * FROM rw_dead_letter"))
    rset.shutdown()


def test_poison_threshold_zero_disables_quarantine(monkeypatch):
    """RW_POISON_THRESHOLD<=0 restores the pre-v3 behavior: the slot
    burns its respawn budget and escalates."""
    from risingwave_tpu.runtime.remote_fragments import RemoteWorkerDied
    monkeypatch.setenv("RW_POISON_PILL", "1:666")
    ROBUSTNESS.poison_threshold = 0
    db = _agg_db()
    rset = remotes_of(db, "ra")[0]
    db.run("INSERT INTO t VALUES (1, 10)")
    for _ in range(3):
        db.tick()
    with pytest.raises(RemoteWorkerDied):
        db.run("INSERT INTO t VALUES (4, 666)")
        for _ in range(12):
            db.tick()
    assert rset.supervisor.quarantined == 0
    rset.shutdown()


# ---------------------------------------------------------------------------
# tentpole 3: coordinated multi-failure respawn (no escalation)
# ---------------------------------------------------------------------------


def _escalations():
    from risingwave_tpu.utils.metrics import REGISTRY
    return sum(float(ln.rsplit(" ", 1)[1])
               for ln in REGISTRY.expose().splitlines()
               if ln.startswith("supervisor_escalations_total{"))


def test_two_simultaneous_join_worker_kills_converge():
    """BOTH workers of one join set die in the same epoch: one
    `_recover_batch` pass quiesces both first, re-seeds both from one
    shared shadow scan, and the MV is bit-identical to an undisturbed
    run — no escalation."""
    n, chunk = 12_000, 64
    ticks = n // (64 * chunk) + 4

    def build(supervise):
        db = Database()
        db.run(AUCTION_SRC.format(n=n, c=chunk))
        db.run(PERSON_SRC.format(n=n, c=chunk))
        db.run("SET streaming_parallelism = 2")
        db.run("SET streaming_placement = 'process'")
        if supervise:
            db.run("SET streaming_supervision TO true")
        db.run("CREATE MATERIALIZED VIEW q3 AS SELECT p.name, p.city,"
               " p.state, a.id FROM auction a JOIN person p"
               " ON a.seller = p.id")
        return db

    db = build(supervise=False)
    for _ in range(ticks):
        db.tick()
    want = sorted(db.query("SELECT * FROM q3"))
    remotes_of(db, "q3")[0].shutdown()

    base_esc = _escalations()
    db = build(supervise=True)
    rfs = remotes_of(db, "q3")[0]
    assert rfs.kind == "join"
    kill_on_next_chunk(rfs, 0)
    kill_on_next_chunk(rfs, 1)
    for _ in range(ticks):
        db.tick()
    assert rfs.supervisor.respawns == 2
    assert rfs.supervisor._escalated is None
    assert _escalations() == base_esc
    assert sorted(db.query("SELECT * FROM q3")) == want
    rfs.shutdown()


def test_simultaneous_agg_and_join_worker_kills_converge():
    """One worker of the JOIN set and one worker of its downstream AGG
    set die in the same epoch — cross-set simultaneous failure. Each
    set's supervisor converges its victim in place; zero escalations;
    result bit-identical to the undisturbed oracle."""
    n, chunk = 12_000, 64
    ticks = n // (64 * chunk) + 4
    mv = ("CREATE MATERIALIZED VIEW qa AS SELECT p.state, count(*) AS c"
          " FROM auction a JOIN person p ON a.seller = p.id"
          " GROUP BY p.state")

    def build(supervise):
        db = Database()
        db.run(AUCTION_SRC.format(n=n, c=chunk))
        db.run(PERSON_SRC.format(n=n, c=chunk))
        db.run("SET streaming_parallelism = 2")
        db.run("SET streaming_placement = 'process'")
        if supervise:
            db.run("SET streaming_supervision TO true")
        db.run(mv)
        return db

    db = build(supervise=False)
    for _ in range(ticks):
        db.tick()
    want = sorted(db.query("SELECT * FROM qa"))
    for r in remotes_of(db, "qa"):
        r.shutdown()

    base_esc = _escalations()
    db = build(supervise=True)
    sets = remotes_of(db, "qa")
    kinds = {r.kind for r in sets}
    assert kinds == {"join", "stateful"}, \
        f"plan must place BOTH fragment kinds remotely (got {kinds})"
    for r in sets:
        kill_on_next_chunk(r, 0)
    for _ in range(ticks + 2):
        db.tick()
    for r in sets:
        assert r.supervisor is not None
        assert r.supervisor.respawns >= 1, r.kind
        assert r.supervisor._escalated is None, r.kind
    assert _escalations() == base_esc
    assert sorted(db.query("SELECT * FROM qa")) == want
    for r in sets:
        r.shutdown()


# ---------------------------------------------------------------------------
# tentpole 4: durable sink journal across a coordinator kill + restart
# ---------------------------------------------------------------------------


def _changelog_net(path):
    """Net row multiset of a jsonl changelog; multiplicities must never
    go negative (a duplicate `+` inflates one; a stale `-` sinks one)."""
    state = {}
    for ln in open(path):
        rec = json.loads(ln)
        row = tuple(str(rec["row"][k]) for k in sorted(rec["row"]))
        state[row] = state.get(row, 0) + (1 if rec["op"] == "+" else -1)
        assert state[row] >= 0, f"negative multiplicity for {row}"
        if state[row] == 0:
            del state[row]
    return sorted(r for row, cnt in state.items() for r in [row] * cnt)


def test_sink_mirror_journal_survives_coordinator_restart(tmp_path):
    """Coordinator kill + restart during a post-respawn refresh window:
    the v1 FULL refresh re-states every owned group, and before v3 the
    restarted coordinator's EMPTY in-memory mirror let those duplicates
    straight into the file. Now the per-pk delivered mirror rebuilds
    from the journaled sink log (epoch-fenced commits), so zero
    duplicate rows reach the file."""
    from risingwave_tpu.utils.metrics import REGISTRY
    ROBUSTNESS.incremental_refresh = False    # the duplicate-generating
    out = tmp_path / "out.jsonl"              # v1 full-refresh path
    d = str(tmp_path / "data")
    db = _agg_db(data_dir=d)
    db.run(f"CREATE SINK snk FROM ra WITH (connector='fs',"
           f" fs.path='{out}')")
    db.run("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30), (4, 40)")
    for _ in range(6):
        db.tick()
    assert _changelog_net(str(out)), "sink must have delivered"
    # coordinator crash: drop the process state, keep the directory
    for r in remotes_of(db, "ra"):
        r.shutdown()
    del db
    # restart: DDL replay rebuilds the job + sink; the sink's delivered
    # mirror must come back from the journal, NOT start empty
    db2 = Database(data_dir=d)
    sink_exec = db2.catalog.get("snk").runtime["sink_exec"]
    assert sink_exec._mirror, "mirror must rebuild from the journal"
    assert sink_exec.mirror_table is not None

    def dropped():
        for ln in REGISTRY.expose().splitlines():
            if ln.startswith("sink_dedupe_dropped_total"):
                return float(ln.rsplit(" ", 1)[1])
        return 0.0

    base_drop = dropped()
    # post-restart respawn: the v1 full refresh re-INSERTs every owned
    # group straight into the sink's changelog. Tick first so the
    # victim DELIVERS post-restart barriers — the refresh path (not the
    # never-delivered verbatim replay) is what must hit the mirror.
    for _ in range(3):
        db2.tick()
    rset = remotes_of(db2, "ra")[0]
    kill_on_next_chunk(rset, 0)
    kill_on_next_chunk(rset, 1)       # whichever slot owns the new keys
    db2.run("INSERT INTO t VALUES (1, 1), (5, 50)")
    for _ in range(8):
        db2.tick()
    assert rset.supervisor.respawns >= 1
    want = sorted(db2.query("SELECT * FROM ra"))
    assert want == sorted(db2.query(
        "SELECT k, count(*), sum(v) FROM t GROUP BY k"))
    # exactly-once at the file: the changelog nets to the MV — no
    # duplicate `+`, no negative multiplicity (asserted in the replay)
    net = _changelog_net(str(out))
    want_rows = sorted(tuple(str(v) for v in (r[1], r[0], r[2]))
                       for r in want)
    assert net == want_rows, (net, want_rows)
    assert dropped() > base_drop, \
        "the journal-rebuilt mirror must be what caught the refresh " \
        "duplicates"
    rset.shutdown()


# ---------------------------------------------------------------------------
# satellites: fused failpoints listed; ledger records fused fires
# ---------------------------------------------------------------------------


def test_risectl_lists_fused_and_poison_failpoints(capsys):
    from risingwave_tpu import ctl
    rc = ctl.main(["failpoints"])
    txt = capsys.readouterr().out
    assert rc == 0
    for point in ("fused.dispatch", "fused.device_sync",
                  "fused.growth_replay", "fused.checkpoint_commit",
                  "worker.poison_pill"):
        assert point in txt, point


def test_fused_fault_lands_in_ledger():
    """Chaos runs over the fused path leave the same exact-replay
    ledger record as host-path runs (`make chaos` ships it on failure)."""
    fp.clear_ledger()
    _fused_q1(arm=("fused.dispatch", 1.0, 0, 1))
    assert any(p == "fused.dispatch" for _o, p, _t, _h in fp.ledger())
    fp.clear_ledger()
