"""Regression: cross-delta pair netting in the device join (found by the
q5 bench oracle). When both join sides change in ONE epoch under a
non-equi condition, dA><B_old can emit the exact pair that A_new><dB
retracts; the barrier's dels-before-ins reordering then resurrected the
net-zero pair in the MV. The fix nets identical rows across the whole
epoch pair set before emission."""
import pytest

from risingwave_tpu.sql import Database

Q5_SHAPE = """CREATE MATERIALIZED VIEW j AS
SELECT A.g, A.num FROM (
    SELECT w, g, count(*) AS num FROM t GROUP BY w, g
) AS A JOIN (
    SELECT w, max(num) AS maxn FROM (
        SELECT w, g, count(*) AS num FROM t GROUP BY w, g
    ) AS C GROUP BY w
) AS B ON A.w = B.w AND A.num >= B.maxn"""


@pytest.mark.parametrize("device", ["on", 8, "off"])
def test_same_epoch_two_sided_change_nets_to_zero(device):
    """One INSERT updates the A side (count b: 2->3) and the B side
    (maxn: 2->4) in the same epoch; b's pair must vanish, not resurrect."""
    db = Database(device=device)
    db.run("CREATE TABLE t (w INT, g VARCHAR)")
    db.run(Q5_SHAPE)
    db.run("INSERT INTO t VALUES (1,'a'),(1,'a'),(1,'b'),(1,'b')")
    assert sorted(db.query("SELECT * FROM j")) == [("a", 2), ("b", 2)]
    db.run("INSERT INTO t VALUES (1,'a'),(1,'a'),(1,'b')")
    assert sorted(db.query("SELECT * FROM j")) == [("a", 4)]
    # and the pair comes back when b catches up to the max
    db.run("INSERT INTO t VALUES (1,'b')")
    assert sorted(db.query("SELECT * FROM j")) == [("a", 4), ("b", 4)]


@pytest.mark.parametrize("device", ["on", "off"])
def test_q5_shape_multi_epoch_parity(device):
    """Longer interleaving: counts racing the max across many epochs must
    keep the device path equal to the batch oracle."""
    db = Database(device=device)
    db.run("CREATE TABLE t (w INT, g VARCHAR)")
    db.run(Q5_SHAPE)
    import numpy as np
    rng = np.random.default_rng(5)
    for _ in range(8):
        rows = ", ".join(
            f"({int(rng.integers(0, 3))}, 'g{int(rng.integers(0, 6))}')"
            for _ in range(20))
        db.run(f"INSERT INTO t VALUES {rows}")
        got = sorted(db.query("SELECT * FROM j"))
        want = sorted(db.query(
            "SELECT A.g, A.num FROM ("
            " SELECT w, g, count(*) AS num FROM t GROUP BY w, g) AS A "
            "JOIN (SELECT w, max(num) AS maxn FROM ("
            " SELECT w, g, count(*) AS num FROM t GROUP BY w, g) AS C "
            "GROUP BY w) AS B "
            "ON A.w = B.w AND A.num >= B.maxn"))
        assert got == want
