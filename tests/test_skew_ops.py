"""Skew-proof device operators (ISSUE 13).

The contract under test: three defenses — local pre-combine
(`PrecombineNode` + AggNode combined mode), hot-key replication
(exchange-level broadcast/salt routing for heavy-hitter join keys), and
barrier-time vnode rebalancing (`FusedJob._maybe_retune` driven by the
`rw_key_skew` evidence) — are each gated by a `DeviceConfig` knob, each
BIT-IDENTICAL to the unskewed path (row order included), and the routing
switch is zero-fresh-compile and survives a checkpoint/recovery cycle.
Plus the satellites: Zipfian datagen (host/device bit-identical),
`risectl skew` offline, and the policy math.

The conftest pins RW_SKEW_STATS / RW_AGG_PRECOMBINE off suite-wide for
compile budget; every test here forces what it needs back on via
monkeypatch (the env is read at CREATE time).
"""
import json
import os
import time

import numpy as np
import pytest

from risingwave_tpu.config import DeviceConfig
from risingwave_tpu.core.vnode import VNODE_COUNT
from risingwave_tpu.sql import Database

N = 4096
CHUNK = 32          # fused epoch = 64 * CHUNK = 2048 events

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           " nexmark.table='bid', nexmark.max.events='{n}',"
           " nexmark.chunk.size='{c}', nexmark.key.dist='{kd}')")
AUCTION_SRC = ("CREATE SOURCE auction (id BIGINT, item_name VARCHAR,"
               " description VARCHAR, initial_bid BIGINT, reserve BIGINT,"
               " date_time TIMESTAMP, expires TIMESTAMP, seller BIGINT,"
               " category BIGINT, extra VARCHAR) WITH (connector='nexmark',"
               " nexmark.table='auction', nexmark.max.events='{n}',"
               " nexmark.chunk.size='{c}')")
PERSON_SRC = ("CREATE SOURCE person (id BIGINT, name VARCHAR,"
              " email_address VARCHAR, credit_card VARCHAR, city VARCHAR,"
              " state VARCHAR, date_time TIMESTAMP, extra VARCHAR)"
              " WITH (connector='nexmark', nexmark.table='person',"
              " nexmark.max.events='{n}', nexmark.chunk.size='{c}')")

Q1_MV = ("CREATE MATERIALIZED VIEW q1a AS SELECT bidder,"
         " count(*) AS n, sum(price) AS dol, max(price) AS top"
         " FROM bid GROUP BY bidder")
Q3_MV = ("CREATE MATERIALIZED VIEW q3a AS SELECT b.auction, b.price,"
         " a.seller, a.category FROM bid b JOIN auction a"
         " ON b.auction = a.id WHERE b.price > 500")
Q5_MV = """CREATE MATERIALIZED VIEW q5 AS
SELECT AuctionBids.auction, AuctionBids.num FROM (
    SELECT bid.auction, count(*) AS num, window_start AS starttime
    FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
    GROUP BY window_start, bid.auction
) AS AuctionBids
JOIN (
    SELECT max(CountBids.num) AS maxn, CountBids.starttime_c
    FROM (
        SELECT count(*) AS num, window_start AS starttime_c
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY bid.auction, window_start
    ) AS CountBids
    GROUP BY CountBids.starttime_c
) AS MaxBids
ON AuctionBids.starttime = MaxBids.starttime_c
   AND AuctionBids.num >= MaxBids.maxn"""


def _arm(monkeypatch, skew="1", pre="1", hot="1", reb="1"):
    monkeypatch.setenv("RW_SKEW_STATS", skew)
    monkeypatch.setenv("RW_AGG_PRECOMBINE", pre)
    monkeypatch.setenv("RW_HOT_KEY_REP", hot)
    monkeypatch.setenv("RW_VNODE_REBALANCE", reb)


def _run(mv_sql, name, shards, srcs=(), kd="zipf:4", n=N, capacity=2048,
         aot=False, data_dir=None, keep=False, threshold=1.2,
         settle=True):
    """One fused run: CREATE, drive to drain, let any staged skew policy
    adopt, return (sorted-as-served rows, job[, db])."""
    db = Database(device=DeviceConfig(capacity=capacity,
                                      mesh_shards=shards,
                                      aot_compile=aot, compile_buckets=0,
                                      rebalance_threshold=threshold),
                  data_dir=data_dir)
    for s in srcs or (BID_SRC,):
        db.run(s.format(n=n, c=CHUNK, kd=kd))
    db.run(mv_sql)
    job = db.catalog.get(name).runtime["fused_job"]
    assert job is not None, f"{name} must fuse"
    for _ in range(n // (64 * CHUNK) + 3):
        db.tick()
    job.sync()
    if settle:
        # a staged policy adopts at the first checkpoint that finds its
        # background pre-warm finished — drive until settled
        for _ in range(60):
            if job._pending_policy is None:
                break
            time.sleep(0.1)
            db.tick()
        db.tick()
    rows = db.query(f"SELECT * FROM {name}")
    return (rows, job, db) if keep else (rows, job, None)


# ---------------------------------------------------------------------------
# policy math (host-side, fast)
# ---------------------------------------------------------------------------


def test_balanced_bounds_properties():
    from risingwave_tpu.device.skew_stats import (SK_BUCKETS,
                                                  balanced_bounds,
                                                  shard_loads,
                                                  shard_skew_ratio)
    from risingwave_tpu.parallel.mesh import vnode_block_bounds
    rng = np.random.RandomState(7)
    for n in (2, 3, 8):
        for _ in range(20):
            occ = rng.randint(0, 50, SK_BUCKETS).tolist()
            b = balanced_bounds(occ, n)
            # contiguous cover: monotone, 0..VNODE_COUNT, right length
            assert len(b) == n + 1 and b[0] == 0 and b[-1] == VNODE_COUNT
            assert all(b[i] <= b[i + 1] for i in range(n))
            # bucket granularity (the evidence resolution)
            per = VNODE_COUNT // SK_BUCKETS
            assert all(v % per == 0 for v in b)
            # never worse than the uniform layout — comparable only
            # when the uniform bounds are themselves bucket-aligned
            # (a non-dividing n splits buckets fractionally, which a
            # bucket-granular partition cannot express)
            uni = tuple(int(v) for v in vnode_block_bounds(n))
            if sum(occ) and all(v % per == 0 for v in uni):
                assert max(shard_loads(occ, b)) \
                    <= max(shard_loads(occ, uni)) + 1e-9
            if sum(occ):
                assert shard_skew_ratio(occ, b) >= 1.0 - 1e-9


def test_balanced_bounds_isolates_hot_bucket():
    from risingwave_tpu.device.skew_stats import (balanced_bounds,
                                                  shard_loads)
    occ = [0] * 16
    occ[5] = 90          # one bucket dominates
    occ[0] = occ[11] = 5
    b = balanced_bounds(occ, 8)
    loads = shard_loads(occ, b)
    assert max(loads) == 90          # can't split below a bucket...
    assert sorted(loads)[-2] <= 5    # ...but nothing shares its shard


def test_shard_loads_split_straddling_bucket():
    from risingwave_tpu.device.skew_stats import shard_loads
    # 3 shards over 256 vnodes: bucket 5 ([80, 96)) straddles the
    # 85/86 boundary — its count splits proportionally
    occ = [0] * 16
    occ[5] = 32
    loads = shard_loads(occ, (0, 85, 170, 256))
    assert abs(loads[0] - 32 * 5 / 16) < 1e-9
    assert abs(loads[1] - 32 * 11 / 16) < 1e-9
    assert loads[2] == 0


def test_sparkline_shape():
    from risingwave_tpu.device.skew_stats import sparkline
    s = sparkline([0, 1, 8, 4])
    assert len(s) == 4 and s[0] == "▁" and s[2] == "█"


# ---------------------------------------------------------------------------
# Zipfian datagen (satellite): host == device, SQL plumbing, FieldGen
# ---------------------------------------------------------------------------


@pytest.mark.skew
def test_zipf_host_device_bit_identity():
    import jax.numpy as jnp
    from risingwave_tpu.connectors.nexmark import (NexmarkConfig,
                                                   NexmarkGenerator,
                                                   _event_kinds)
    from risingwave_tpu.device.nexmark_gen import GenCfg, gen_table
    cfg = NexmarkConfig(key_dist="zipf:1.5")
    ids = np.arange(50_000, dtype=np.int64)
    bids = ids[_event_kinds(ids) == 2]
    host = NexmarkGenerator(cfg).gen_bids(bids)
    dev = gen_table(GenCfg.from_config(cfg), "bid", jnp.asarray(bids))
    assert np.array_equal(host.columns[0].values,
                          np.asarray(dev["auction"]))
    assert np.array_equal(host.columns[1].values,
                          np.asarray(dev["bidder"]))
    # it IS a power law: rank-1 dominates, counts decay
    _, counts = np.unique(host.columns[0].values, return_counts=True)
    top = np.sort(counts)[::-1]
    assert top[0] > 0.15 * counts.sum()
    assert top[0] > 2 * top[1] > 0


def test_zipf_key_dist_validation():
    from risingwave_tpu.device.nexmark_gen import key_dist_s
    assert key_dist_s("zipf:1.5") == 1.5
    assert key_dist_s("zipf") == 1.5
    with pytest.raises(ValueError):
        key_dist_s("zipf:1.0")          # needs s > 1
    with pytest.raises(ValueError):
        key_dist_s("uniform:2")


def test_datagen_fieldgen_zipf():
    from risingwave_tpu.connectors.datagen import FieldGen
    from risingwave_tpu.core import dtypes as T
    g = FieldGen(kind="zipf", start=100, end=200, seed=3, s=2.0)
    col = g.generate(T.INT64, np.arange(20_000, dtype=np.int64))
    vals = np.asarray(col.values)
    assert vals.min() >= 100 and vals.max() < 200
    u, c = np.unique(vals, return_counts=True)
    assert u[np.argmax(c)] == 100        # rank 1 = start, the hot key
    assert c.max() > 0.3 * c.sum()
    # deterministic: same seed, same stream
    again = np.asarray(g.generate(T.INT64,
                                  np.arange(20_000, dtype=np.int64)).values)
    assert np.array_equal(vals, again)


def test_datagen_sql_zipf_option():
    db = Database()
    db.run("CREATE SOURCE s (k BIGINT, v BIGINT) WITH ("
           "connector='datagen', fields.k.kind='zipf:2.0',"
           " fields.k.start='1', fields.k.end='50',"
           " datagen.max.rows='4096', rows.per.poll='1024')")
    db.run("CREATE MATERIALIZED VIEW zz AS SELECT k, count(*) AS c"
           " FROM s GROUP BY k")
    for _ in range(8):
        db.tick()
    counts = {int(k): int(c) for k, c in db.query("SELECT * FROM zz")}
    assert min(counts) >= 1 and max(counts) < 50
    total = sum(counts.values())
    assert total == 4096
    assert counts[1] == max(counts.values())   # start = rank 1, hot
    assert counts[1] > 0.3 * total


def test_nexmark_key_dist_conflict_rejected():
    db = Database()
    db.run(BID_SRC.format(n=256, c=32, kd="zipf:2"))
    with pytest.raises(ValueError):
        db.run(AUCTION_SRC.format(n=256, c=32)
               .replace("nexmark.table='auction'",
                        "nexmark.table='auction', "
                        "nexmark.key.dist='zipf:3'"))


# ---------------------------------------------------------------------------
# defense 1: local pre-combine (1-shard; mesh identity below)
# ---------------------------------------------------------------------------


@pytest.mark.skew
def test_precombine_bit_identity_and_noop_path(monkeypatch):
    from risingwave_tpu.device.fused import PrecombineNode
    _arm(monkeypatch, pre="0")
    r_off, _, _ = _run(Q1_MV, "q1a", 1)
    _arm(monkeypatch, pre="1")
    r_on, job, _ = _run(Q1_MV, "q1a", 1)
    assert any(isinstance(nd, PrecombineNode) for nd in job.program.nodes)
    assert r_off == r_on                 # bit-identical, order included
    # all-unique keys (person id is unique per row): pre-combine is a
    # pure no-op pass-through — same rows either way
    mv = ("CREATE MATERIALIZED VIEW pp AS SELECT id, count(*) AS c"
          " FROM person GROUP BY id")
    _arm(monkeypatch, pre="0")
    u_off, _, _ = _run(mv, "pp", 1, srcs=(PERSON_SRC,), n=1024)
    _arm(monkeypatch, pre="1")
    u_on, ujob, _ = _run(mv, "pp", 1, srcs=(PERSON_SRC,), n=1024)
    assert any(isinstance(nd, PrecombineNode) for nd in ujob.program.nodes)
    assert u_off == u_on and len(u_on) > 0
    # unique keys: combined rows == raw rows (rows_out == rows_in)
    pre_i = next(i for i, nd in enumerate(ujob.program.nodes)
                 if isinstance(nd, PrecombineNode))
    st = ujob.program.node_stats(pre_i, ujob._stat_totals)
    assert st["rows_out"] == st["rows_in"] > 0


@pytest.mark.skew
def test_precombine_skipped_for_exact_minmax(monkeypatch):
    # retractable min/max (multiset state) is NOT exactly combinable by
    # group alone — the planner must keep the raw path
    from risingwave_tpu.device.fused import AggNode, PrecombineNode
    _arm(monkeypatch)
    mv = ("CREATE MATERIALIZED VIEW mm AS SELECT starttime_c,"
          " max(num) AS maxn FROM ("
          "   SELECT count(*) AS num, window_start AS starttime_c"
          "   FROM HOP(bid, date_time, INTERVAL '2' SECOND,"
          "            INTERVAL '10' SECOND)"
          "   GROUP BY bid.auction, window_start) t"
          " GROUP BY starttime_c")
    _, job, _ = _run(mv, "mm", 1, n=1024)
    aggs = [nd for nd in job.program.nodes if isinstance(nd, AggNode)]
    pres = [nd for nd in job.program.nodes
            if isinstance(nd, PrecombineNode)]
    # first-level count agg combines; the retractable max agg does not
    assert any(a.combined for a in aggs)
    assert any(not a.combined and a.spec.minputs for a in aggs)
    assert len(pres) == sum(a.combined for a in aggs)


# ---------------------------------------------------------------------------
# mesh defenses: bit-identity + rebalance + zero-compile + recovery
# ---------------------------------------------------------------------------


@pytest.mark.mesh
@pytest.mark.skew
def test_q1_mesh_defenses_bit_identity_and_rebalance(
        monkeypatch, tmp_path):
    d = str(tmp_path / "d")
    # defenses OFF at both shard counts: the reference pair
    _arm(monkeypatch, pre="0", hot="0", reb="0")
    r1, _, _ = _run(Q1_MV, "q1a", 1)
    r8_off, _, _ = _run(Q1_MV, "q1a", 8)
    assert r1 == r8_off
    # defenses ON at 8 shards, AOT on, persisted: the zipf:4 bidder set
    # is small and lumpy across vnode buckets, so occupancy crosses the
    # 1.2 threshold and the job rebalances at a checkpoint
    _arm(monkeypatch)
    r8_on, job, db = _run(Q1_MV, "q1a", 8, aot=True, data_dir=d,
                          keep=True)
    assert r8_on == r1                  # bit-identical THROUGH the switch
    assert job.rebalances >= 1
    assert job.program.vnode_bounds is not None
    bounds = job.program.vnode_bounds
    # the adopted bounds even out the per-shard load implied by the
    # occupancy histogram (vs the uniform layout)
    from risingwave_tpu.device.skew_stats import (SK_BUCKETS,
                                                  shard_skew_ratio)
    from risingwave_tpu.parallel.mesh import vnode_block_bounds
    agg_i = next(i for i, nd in enumerate(job.program.nodes)
                 if nd.skew and nd.exch is not None)
    st = job.program.node_stats(agg_i, job._stat_totals)
    occ = [st[f"skv{b}"] for b in range(SK_BUCKETS)]
    uni = tuple(int(v) for v in vnode_block_bounds(8))
    assert shard_skew_ratio(occ, bounds) \
        <= shard_skew_ratio(occ, uni) + 1e-9
    # rw_key_skew carries the shard-load surface
    skew_rows = db.query("SELECT * FROM rw_key_skew WHERE job = 'q1a'")
    assert any(r[3] == "shard_load" for r in skew_rows)
    assert any(r[3] == "shard_skew" for r in skew_rows)
    # ---- survives a checkpoint/recovery cycle -----------------------
    for _ in range(3):
        db.tick()
    r_live = db.query("SELECT * FROM q1a")
    db2 = Database(device=DeviceConfig(capacity=2048, mesh_shards=8,
                                       aot_compile=True,
                                       compile_buckets=0,
                                       rebalance_threshold=1.2),
                   data_dir=d)
    job2 = db2.catalog.get("q1a").runtime["fused_job"]
    assert job2.program.vnode_bounds == bounds
    assert job2.rebalances >= 1
    assert db2.query("SELECT * FROM q1a") == r_live == r1


@pytest.mark.mesh
@pytest.mark.skew
def test_rebalance_switch_is_zero_fresh_compile(monkeypatch):
    from risingwave_tpu.device import shard_exec
    from risingwave_tpu.device.compile_service import get_service
    # rebalancing held OFF while the job drives to drain, so every
    # node-step signature compiles up front and the measurement window
    # below brackets EXACTLY the stage -> pre-warm -> adopt sequence
    _arm(monkeypatch, hot="0", reb="0")
    db = Database(device=DeviceConfig(capacity=2048, mesh_shards=8,
                                      aot_compile=True, compile_buckets=0,
                                      rebalance_threshold=1.2))
    db.run(BID_SRC.format(n=N, c=CHUNK, kd="zipf:4"))
    db.run(Q1_MV)
    job = db.catalog.get("q1a").runtime["fused_job"]
    svc = get_service()
    for _ in range(N // (64 * CHUNK) + 2):
        db.tick()
    job.sync()
    db.tick()                            # a checkpoint with fresh stats
    svc.wait_idle(60)
    before = svc.summary()
    e_before = shard_exec.exchange_stats()
    job.rebalance = True                 # open the policy loop
    for _ in range(200):
        if job.rebalances:
            break
        if job._pending_policy is not None:
            assert job._pending_policy[2].wait(60), \
                "exchange pre-warm hung"
        db.tick()
        time.sleep(0.02)
    assert job.rebalances >= 1, "skew policy never adopted"
    after = svc.summary()
    e_after = shard_exec.exchange_stats()
    # zero fresh compiles at the switch: no node-step compile was
    # requested (the signatures never changed) and the re-routed
    # exchange dispatched on its pre-warmed executable
    assert after["compiles"] == before["compiles"]
    assert after["pending"] == 0
    assert e_after["inline_keys"] == e_before["inline_keys"]
    assert e_after["aot_hits"] > e_before["aot_hits"]


# ---------------------------------------------------------------------------
# defense 2: hot-key replication (99%-one-key join)
# ---------------------------------------------------------------------------


@pytest.mark.mesh
@pytest.mark.skew
def test_q3_hot_key_replication_99pct_one_key(monkeypatch):
    from risingwave_tpu.device.fused import JoinNode
    # zipf:8 ~> 99% of bids hit auction rank 1 — the one-hot-key case
    _arm(monkeypatch, reb="0")
    r1, _, _ = _run(Q3_MV, "q3a", 1, srcs=(BID_SRC, AUCTION_SRC),
                    kd="zipf:8")
    _arm(monkeypatch, hot="0", reb="0")
    r8_off, _, _ = _run(Q3_MV, "q3a", 8, srcs=(BID_SRC, AUCTION_SRC),
                        kd="zipf:8")
    _arm(monkeypatch, reb="0")
    r8_on, job, _ = _run(Q3_MV, "q3a", 8, srcs=(BID_SRC, AUCTION_SRC),
                         kd="zipf:8")
    joins = [nd for nd in job.program.nodes if isinstance(nd, JoinNode)]
    assert joins and all(nd.hotrep for nd in joins)
    armed = [nd for nd in joins if nd.hot_keys]
    assert armed, "heavy hitter never promoted to a hot key"
    # the hot key is the dominant auction (packed key = id - offset = 0
    # for the first auction) and the dimension side (auction) broadcasts
    assert armed[0].hot_keys == (0,)
    assert armed[0].hot_rep_side == 1
    assert job.rebalances >= 1           # the policy switch happened
    assert len(r1) > 0
    assert r1 == r8_off == r8_on         # bit-identical, order included


# ---------------------------------------------------------------------------
# q5: every defense at once on the hardest fused shape
# ---------------------------------------------------------------------------


@pytest.mark.mesh
@pytest.mark.skew
def test_q5_all_defenses_bit_identity(monkeypatch):
    _arm(monkeypatch, pre="0", hot="0", reb="0")
    r1, _, _ = _run(Q5_MV, "q5", 1)
    _arm(monkeypatch)
    r8, job, _ = _run(Q5_MV, "q5", 8)
    from risingwave_tpu.device.fused import AggNode
    assert any(getattr(nd, "combined", False) for nd in job.program.nodes
               if isinstance(nd, AggNode))
    assert r1 == r8


# ---------------------------------------------------------------------------
# satellite: risectl skew (offline, dead data dir)
# ---------------------------------------------------------------------------


@pytest.mark.mesh
@pytest.mark.skew
def test_ctl_skew_offline(monkeypatch, tmp_path, capsys):
    from risingwave_tpu import ctl
    _arm(monkeypatch, hot="0")
    d = str(tmp_path / "d")
    _run(Q1_MV, "q1a", 8, data_dir=d)
    # the database object is GONE — the dir is dead, the snapshot stays
    rc = ctl.main(["skew", "--data-dir", d])
    out = capsys.readouterr().out
    assert rc == 0
    assert "job q1a" in out and "skew_ratio" in out
    assert "occ" in out
    rc = ctl.main(["skew", "q1a", "--data-dir", d, "--json"])
    doc = json.loads(capsys.readouterr().out)
    assert "q1a" in doc
    assert any(r[2] == "vnode_occ" for r in doc["q1a"]["rows"])
    assert ctl.main(["skew", "nosuch", "--data-dir", d]) == 1
    assert ctl.main(["skew", "--data-dir", str(tmp_path)]) == 1


# ---------------------------------------------------------------------------
# satellite: device-side gather for sharded MV SELECT pulls
# ---------------------------------------------------------------------------


@pytest.mark.mesh
@pytest.mark.skew
def test_sharded_pull_gather_matches_host_merge(monkeypatch):
    _arm(monkeypatch, hot="0", reb="0")
    # zipf:1.3 keeps hundreds of distinct groups live, so the stale-
    # bound fallback below is genuinely exercised (total > 256)
    rows, job, db = _run(Q1_MV, "q1a", 8, kd="zipf:1.3", keep=True)
    # the in-program gather path served the SELECT; force the host-merge
    # fallback (no live bound) and compare bit-for-bit
    from risingwave_tpu.device.shard_exec import merge_keyed_pull
    st = job.states[job.pull.node_idx]
    dts = [c.acc_dtype for c in job.pull.agg.spec.calls]
    k_host, c_host, u_host = merge_keyed_pull(st, job.program.mesh, dts)
    need = job._pull_need()
    assert need > 0
    k_dev, c_dev, u_dev = merge_keyed_pull(st, job.program.mesh, dts,
                                           live_bound=need * 8)
    assert np.array_equal(k_host, k_dev)
    for a, b in zip(c_host, c_dev):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(u_host, u_dev):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    # a stale (too-small) bound falls back to the host merge — same rows
    k_fb, _, _ = merge_keyed_pull(st, job.program.mesh, dts, live_bound=1)
    assert np.array_equal(k_fb, k_host)
