"""Postgres wire protocol server (SURVEY L9 surface; reference
src/utils/pgwire/src/pg_server.rs:46). No postgres client library is
available in this image, so the test speaks protocol v3 directly — which
also pins the exact bytes on the wire."""
import socket
import struct

import pytest

from risingwave_tpu.pgwire import PgServer
from risingwave_tpu.sql import Database


class MiniClient:
    """Just enough of the v3 protocol to converse."""

    def __init__(self, host, port):
        self.sock = socket.create_connection((host, port), timeout=10)
        self.buf = b""

    def _recv(self, n):
        while len(self.buf) < n:
            got = self.sock.recv(65536)
            if not got:
                raise ConnectionError
            self.buf += got
        out, self.buf = self.buf[:n], self.buf[n:]
        return out

    def startup(self):
        params = b"user\0tester\0database\0dev\0\0"
        body = struct.pack(">I", 196608) + params
        self.sock.sendall(struct.pack(">I", len(body) + 4) + body)
        msgs = self.read_until(b"Z")
        assert msgs[0][0] == b"R"          # AuthenticationOk
        assert struct.unpack(">I", msgs[0][1])[0] == 0
        assert any(t == b"K" for t, _ in msgs)
        return msgs

    def send(self, tag, payload=b""):
        self.sock.sendall(tag + struct.pack(">I", len(payload) + 4) + payload)

    def read_msg(self):
        tag = self._recv(1)
        (ln,) = struct.unpack(">I", self._recv(4))
        return tag, self._recv(ln - 4)

    def read_until(self, stop_tag):
        msgs = []
        while True:
            t, b = self.read_msg()
            msgs.append((t, b))
            if t == stop_tag:
                return msgs

    def query(self, sql):
        self.send(b"Q", sql.encode() + b"\0")
        return self.read_until(b"Z")

    def rows(self, msgs):
        out = []
        for t, b in msgs:
            if t != b"D":
                continue
            (n,) = struct.unpack(">H", b[:2])
            pos, row = 2, []
            for _ in range(n):
                (ln,) = struct.unpack(">i", b[pos:pos + 4])
                pos += 4
                if ln < 0:
                    row.append(None)
                else:
                    row.append(b[pos:pos + ln].decode())
                    pos += ln
            out.append(tuple(row))
        return out


@pytest.fixture
def server():
    db = Database()
    srv = PgServer(db).start()
    yield srv
    srv.stop()


def test_startup_and_simple_query(server):
    c = MiniClient(server.host, server.port)
    c.startup()
    msgs = c.query("CREATE TABLE t (k INT, v BIGINT, s VARCHAR)")
    assert any(t == b"C" and b.startswith(b"CREATE TABLE")
               for t, b in msgs)
    msgs = c.query("INSERT INTO t VALUES (1, 10, 'a'), (2, NULL, 'b')")
    assert any(t == b"C" and b.startswith(b"INSERT 0 2") for t, b in msgs)
    msgs = c.query("SELECT k, v, s FROM t")
    # RowDescription carries names + OIDs
    t_msg = next(b for t, b in msgs if t == b"T")
    (ncols,) = struct.unpack(">H", t_msg[:2])
    assert ncols == 3
    assert b"k\0" in t_msg and b"v\0" in t_msg and b"s\0" in t_msg
    rows = sorted(c.rows(msgs))
    assert rows == [("1", "10", "a"), ("2", None, "b")]
    assert any(t == b"C" and b.startswith(b"SELECT 2") for t, b in msgs)


def test_error_keeps_connection_usable(server):
    c = MiniClient(server.host, server.port)
    c.startup()
    msgs = c.query("SELECT * FROM no_such_table")
    assert any(t == b"E" for t, b in msgs)
    assert msgs[-1][0] == b"Z"                 # ReadyForQuery after error
    msgs = c.query("SELECT 1 + 1")
    assert c.rows(msgs) == [("2",)]


def test_streaming_ddl_over_the_wire(server):
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE t (k INT, v BIGINT)")
    msgs = c.query("CREATE MATERIALIZED VIEW mv AS "
                   "SELECT k, sum(v) AS s FROM t GROUP BY k")
    assert any(t == b"C" for t, b in msgs)
    c.query("INSERT INTO t VALUES (1, 10), (1, 5), (2, 7)")
    rows = sorted(c.rows(c.query("SELECT * FROM mv")))
    assert rows == [("1", "15"), ("2", "7")]


def test_ssl_request_declined_then_plain(server):
    sock = socket.create_connection((server.host, server.port), timeout=10)
    body = struct.pack(">I", 80877103)         # SSLRequest
    sock.sendall(struct.pack(">I", len(body) + 4) + body)
    assert sock.recv(1) == b"N"
    sock.close()


def test_extended_protocol(server):
    """Parse/Bind/Describe/Execute/Sync: Describe answers the real
    RowDescription, Execute sends only rows + completion."""
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE t (k INT)")
    c.query("INSERT INTO t VALUES (5)")
    c.send(b"P", b"s1\0SELECT k FROM t\0" + struct.pack(">H", 0))
    c.send(b"B", b"\0s1\0" + struct.pack(">HHH", 0, 0, 0))
    c.send(b"D", b"P\0")
    c.send(b"E", b"\0" + struct.pack(">I", 0))
    c.send(b"S")
    msgs = c.read_until(b"Z")
    tags = [t for t, _ in msgs]
    assert tags[:3] == [b"1", b"2", b"T"]       # Parse, Bind, RowDescription
    assert tags.count(b"T") == 1                # Execute must NOT resend it
    assert c.rows(msgs) == [("5",)]
    assert any(t == b"C" and b.startswith(b"SELECT 1") for t, b in msgs)
    # Close the statement; executing its portal afterwards errors cleanly
    c.send(b"C", b"Ss1\0")
    c.send(b"B", b"\0s1\0" + struct.pack(">HHH", 0, 0, 0))
    c.send(b"E", b"\0" + struct.pack(">I", 0))
    c.send(b"S")
    msgs = c.read_until(b"Z")
    assert any(t == b"E" for t, _ in msgs)      # portal does not exist


def test_show_and_explain_return_rows(server):
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE t (k INT)")
    rows = c.rows(c.query("SHOW TABLES"))
    assert rows == [("t",)]
    plan = c.rows(c.query("EXPLAIN SELECT k FROM t"))
    assert any("Scan(t)" in r[0] for r in plan)
    assert c.rows(c.query("SHOW timezone")) == [("UTC",)]


def test_empty_query_response(server):
    c = MiniClient(server.host, server.port)
    c.startup()
    msgs = c.query("   ")
    assert msgs[0][0] == b"I"                   # EmptyQueryResponse
    assert msgs[-1][0] == b"Z"


def test_multi_statement_ddl_log_replays_once(server, tmp_path):
    """Regression (review finding): a multi-statement simple query must
    DDL-log only the per-statement text, or recovery re-runs the INSERT."""
    from risingwave_tpu.sql import Database
    db = Database(data_dir=str(tmp_path))
    srv = PgServer(db).start()
    try:
        c = MiniClient(srv.host, srv.port)
        c.startup()
        c.query("CREATE TABLE a (x INT); INSERT INTO a VALUES (1)")
        assert c.rows(c.query("SELECT count(*) FROM a")) == [("1",)]
    finally:
        srv.stop()
    db2 = Database(data_dir=str(tmp_path))
    assert db2.query("SELECT count(*) FROM a") == [(1,)]


def test_two_concurrent_connections(server):
    a = MiniClient(server.host, server.port)
    b = MiniClient(server.host, server.port)
    a.startup()
    b.startup()
    a.query("CREATE TABLE shared (x INT)")
    a.query("INSERT INTO shared VALUES (7)")
    assert b.rows(b.query("SELECT x FROM shared")) == [("7",)]


def test_describe_statement_vs_portal(server):
    """Describe('S') must describe the *parsed statement* (pgjdbc's
    Parse -> Describe(S) -> Bind -> Execute order): ParameterDescription
    then RowDescription, before any Bind exists."""
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE t (k INT, s VARCHAR)")
    c.query("INSERT INTO t VALUES (7, 'x')")
    c.send(b"P", b"s1\0SELECT k, s FROM t\0" + struct.pack(">H", 0))
    c.send(b"D", b"Ss1\0")                      # statement, not portal
    c.send(b"B", b"\0s1\0" + struct.pack(">HHH", 0, 0, 0))
    c.send(b"E", b"\0" + struct.pack(">I", 0))
    c.send(b"S")
    msgs = c.read_until(b"Z")
    tags = [t for t, _ in msgs]
    # Parse, ParameterDescription, RowDescription, Bind, rows...
    assert tags[:4] == [b"1", b"t", b"T", b"2"], tags
    t_msg = next(b for t, b in msgs if t == b"T")
    assert struct.unpack(">H", t_msg[:2])[0] == 2
    assert c.rows(msgs) == [("7", "x")]


def test_extended_error_discards_until_sync(server):
    """After an extended-protocol error the server must skip all messages
    until Sync — a pipelined statement after the failed one must NOT run."""
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE tt (k INT)")
    # Pipeline: failing execute, then an INSERT that must be discarded.
    c.send(b"P", b"bad\0SELECT * FROM missing_table\0" + struct.pack(">H", 0))
    c.send(b"B", b"\0bad\0" + struct.pack(">HHH", 0, 0, 0))
    c.send(b"E", b"\0" + struct.pack(">I", 0))
    c.send(b"P", b"ins\0INSERT INTO tt VALUES (1)\0" + struct.pack(">H", 0))
    c.send(b"B", b"\0ins\0" + struct.pack(">HHH", 0, 0, 0))
    c.send(b"E", b"\0" + struct.pack(">I", 0))
    c.send(b"S")
    msgs = c.read_until(b"Z")
    tags = [t for t, _ in msgs]
    assert b"E" in tags
    # Nothing after the error except ReadyForQuery (no ParseComplete/
    # BindComplete/CommandComplete from the pipelined INSERT).
    assert tags[tags.index(b"E") + 1:] == [b"Z"], tags
    rows = c.rows(c.query("SELECT count(*) FROM tt"))
    assert rows == [("0",)]                     # the INSERT never ran


def test_describe_unknown_table_sends_error_not_disconnect(server):
    """Describe of a parseable SELECT over a missing table must answer
    ErrorResponse (then discard until Sync), not kill the connection."""
    c = MiniClient(server.host, server.port)
    c.startup()
    c.send(b"P", b"s1\0SELECT * FROM missing_table\0" + struct.pack(">H", 0))
    c.send(b"D", b"Ss1\0")
    c.send(b"S")
    msgs = c.read_until(b"Z")
    tags = [t for t, _ in msgs]
    assert b"E" in tags, tags
    # connection still usable
    assert c.rows(c.query("SELECT 1 + 1")) == [("2",)]


def test_extended_query_with_parameters(server):
    """Parse with $n placeholders + Bind text-format values + Execute —
    the default mode of psycopg/pgjdbc prepared statements
    (pg_extended.rs analog)."""
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE pt (a BIGINT, b VARCHAR)")
    c.query("INSERT INTO pt VALUES (1, 'x'), (2, 'y'), (3, 'z''q')")
    c.query("FLUSH")

    def send_parse(name, sql, oids=()):
        payload = name + b"\0" + sql + b"\0" + struct.pack(">H", len(oids))
        for o in oids:
            payload += struct.pack(">I", o)
        c.send(b"P", payload)

    def send_bind(portal, stmt, values):
        payload = portal + b"\0" + stmt + b"\0" + struct.pack(">H", 0)
        payload += struct.pack(">H", len(values))
        for v in values:
            if v is None:
                payload += struct.pack(">i", -1)
            else:
                payload += struct.pack(">I", len(v)) + v
        payload += struct.pack(">H", 0)
        c.send(b"B", payload)

    # int param, bigint OID declared
    send_parse(b"s1", b"SELECT b FROM pt WHERE a = $1", (20,))
    c.send(b"D", b"Ss1\0")
    send_bind(b"", b"s1", [b"2"])
    c.send(b"E", b"\0" + struct.pack(">I", 0))
    c.send(b"S", b"")
    msgs = c.read_until(b"Z")
    tags = [t for t, _ in msgs]
    assert b"t" in tags, "ParameterDescription expected"
    pd = next(b for t, b in msgs if t == b"t")
    assert struct.unpack(">H", pd[:2])[0] == 1
    assert struct.unpack(">I", pd[2:6])[0] == 20
    assert c.rows(msgs) == [("y",)]

    # string param with embedded quote, unknown OID; reuse the statement
    send_parse(b"s2", b"SELECT a FROM pt WHERE b = $1")
    send_bind(b"", b"s2", [b"z'q"])
    c.send(b"E", b"\0" + struct.pack(">I", 0))
    c.send(b"S", b"")
    assert c.rows(c.read_until(b"Z")) == [("3",)]

    # NULL parameter: a = NULL matches nothing
    send_bind(b"", b"s1", [None])
    c.send(b"E", b"\0" + struct.pack(">I", 0))
    c.send(b"S", b"")
    assert c.rows(c.read_until(b"Z")) == []

    # missing parameter -> error, connection stays usable
    send_bind(b"", b"s1", [])
    c.send(b"E", b"\0" + struct.pack(">I", 0))
    c.send(b"S", b"")
    msgs = c.read_until(b"Z")
    assert any(t == b"E" for t, _ in msgs)
    assert c.rows(c.query("SELECT count(*) FROM pt")) == [("3",)]


def _bind_payload(portal=b"", stmt=b"", fmts=(), values=(), rfmts=()):
    out = portal + b"\0" + stmt + b"\0"
    out += struct.pack(">H", len(fmts))
    for f in fmts:
        out += struct.pack(">H", f)
    out += struct.pack(">H", len(values))
    for v in values:
        if v is None:
            out += struct.pack(">i", -1)
        else:
            out += struct.pack(">I", len(v)) + v
    out += struct.pack(">H", len(rfmts))
    for f in rfmts:
        out += struct.pack(">H", f)
    return out


def test_prepared_statement_plan_once_execute_many(server):
    """Parse once, Bind/Execute many times with different parameters —
    no re-parse per Execute (pg_extended.rs plan-once contract)."""
    import risingwave_tpu.sql.parser as P
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE pt (k BIGINT, v BIGINT)")
    c.query("INSERT INTO pt VALUES (1, 10), (2, 20), (3, 30)")
    c.send(b"P", b"ps\0SELECT v FROM pt WHERE k = $1\0"
           + struct.pack(">HI", 1, 20))
    c.send(b"S")
    c.read_until(b"Z")
    calls = {"n": 0}
    orig = P.parse_sql

    def counting(sql):
        calls["n"] += 1
        return orig(sql)
    P.parse_sql = counting
    try:
        for k, want in ((b"1", "10"), (b"2", "20"), (b"3", "30")):
            c.send(b"B", _bind_payload(stmt=b"ps", values=(k,)))
            c.send(b"E", b"\0" + struct.pack(">I", 0))
            c.send(b"S")
            msgs = c.read_until(b"Z")
            assert c.rows(msgs) == [(want,)], (k, c.rows(msgs))
    finally:
        P.parse_sql = orig
    assert calls["n"] == 0, f"{calls['n']} re-parses during Execute"


def test_binary_parameters(server):
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE bt (k BIGINT, f DOUBLE PRECISION)")
    c.query("INSERT INTO bt VALUES (7, 1.5), (8, 2.5)")
    # int8 binary + float8 binary
    c.send(b"P", b"bs\0SELECT f FROM bt WHERE k = $1 AND f < $2\0"
           + struct.pack(">HII", 2, 20, 701))
    c.send(b"B", _bind_payload(stmt=b"bs", fmts=(1, 1),
                               values=(struct.pack(">q", 7),
                                       struct.pack(">d", 99.0))))
    c.send(b"E", b"\0" + struct.pack(">I", 0))
    c.send(b"S")
    msgs = c.read_until(b"Z")
    assert c.rows(msgs) == [("1.5",)], c.rows(msgs)


def test_portal_row_limit_and_suspend(server):
    """Execute with max_rows fetches incrementally: PortalSuspended
    between fetches, CommandComplete at exhaustion."""
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE rt (k BIGINT)")
    c.query("INSERT INTO rt VALUES (1), (2), (3), (4), (5)")
    c.send(b"P", b"rs\0SELECT k FROM rt ORDER BY k\0" + struct.pack(">H", 0))
    c.send(b"B", _bind_payload(stmt=b"rs"))
    c.send(b"E", b"\0" + struct.pack(">I", 2))   # fetch 2
    c.send(b"H")
    got, tags = [], []
    while True:
        t, b = c.read_msg()
        tags.append(t)
        if t == b"D":
            got.append(c.rows([(t, b)])[0][0])
        if t in (b"s", b"C"):
            break
    assert got == ["1", "2"] and tags[-1] == b"s", (got, tags)
    c.send(b"E", b"\0" + struct.pack(">I", 2))   # next 2
    c.send(b"E", b"\0" + struct.pack(">I", 0))   # rest
    c.send(b"S")
    msgs = c.read_until(b"Z")
    vals = [r[0] for r in c.rows(msgs)]
    assert vals == ["3", "4", "5"], vals
    assert any(t == b"s" for t, _ in msgs)       # second fetch suspended
    assert any(t == b"C" for t, _ in msgs)       # final completed


# ---------------------------------------------------------------------------
# COPY <table> FROM STDIN (ISSUE 15: the firehose entry point)
# ---------------------------------------------------------------------------


def _copy(c, sql, chunks, done=True):
    c.send(b"Q", sql.encode() + b"\0")
    t, b = c.read_msg()
    if t != b"G":
        # refusal path: drain to ready, hand back the error
        msgs = [(t, b)] + c.read_until(b"Z")
        return None, msgs
    for ch in chunks:
        c.send(b"d", ch)
    c.send(b"c" if done else b"f", b"" if done else b"stop\0")
    return (t, b), c.read_until(b"Z")


def test_copy_from_stdin_text_and_csv(server):
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE cp (a BIGINT, b VARCHAR, f DOUBLE PRECISION)")
    g, msgs = _copy(c, "COPY cp FROM STDIN",
                    [b"1\tone\t1.5\n2\t\\N\t2.5\n", b"3\tthr", b"ee\t3.5\n"])
    assert g is not None and g[1][0:1] == b"\x00"   # text-format response
    assert any(t == b"C" and b.startswith(b"COPY 3") for t, b in msgs)
    g, msgs = _copy(c, "COPY cp FROM STDIN WITH (FORMAT csv)",
                    [b'4,"fo,ur",4.5\n5,,5.5\n'])
    assert g is not None
    assert any(t == b"C" and b.startswith(b"COPY 2") for t, b in msgs)
    c.query("FLUSH")
    rows = sorted(c.rows(c.query("SELECT a, b, f FROM cp")))
    assert rows == [("1", "one", "1.5"), ("2", None, "2.5"),
                    ("3", "three", "3.5"), ("4", "fo,ur", "4.5"),
                    ("5", None, "5.5")]


def test_copy_unsupported_format_sqlstate_0a000(server):
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE cp2 (a BIGINT)")
    for sql in ("COPY cp2 FROM STDIN (FORMAT binary)",
                "COPY cp2 FROM STDIN WITH (FORMAT parquet)"):
        g, msgs = _copy(c, sql, [])
        assert g is None, "unsupported format must refuse BEFORE CopyIn"
        err = next(b for t, b in msgs if t == b"E")
        assert b"0A000" in err
    # connection stays usable after the refusal
    assert any(t == b"C" for t, _ in c.query("SELECT 1"))


def test_copy_fail_and_bad_rows(server):
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE cp3 (a BIGINT, b VARCHAR)")
    # client aborts: CopyFail -> ErrorResponse, connection usable
    g, msgs = _copy(c, "COPY cp3 FROM STDIN", [b"1\tx\n"], done=False)
    assert g is not None and any(t == b"E" for t, _ in msgs)
    # malformed rows: error after the stream, not a hang
    g, msgs = _copy(c, "COPY cp3 FROM STDIN", [b"1\tonly\n1\ttoo\tmany\n"])
    assert g is not None and any(t == b"E" for t, _ in msgs)
    assert any(t == b"C" for t, _ in c.query("SELECT 1"))


def test_copy_rides_the_admission_gate(server):
    """The firehose enters through the same per-source AdmissionBucket
    as connector sources: admitted rows are accounted, and on the
    shedding rung unadmitted batches drop with a durable audit row."""
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE fh (a BIGINT)")
    g, msgs = _copy(c, "COPY fh FROM STDIN", [b"1\n2\n3\n"])
    assert any(t == b"C" and b.startswith(b"COPY 3") for t, b in msgs)
    db = server.db
    bucket = db._overload.bucket("fh")
    assert bucket.admitted_rows == 3 and bucket.lag == 0
    # force the shedding rung: the next batch drops, audited
    bucket.state = "shedding"
    bucket.shed_enabled = True
    bucket.tokens = 0
    bucket._copy_epoch = db.injector.epoch.curr     # pin: no refill
    verdict, n = db.copy_chunk("fh", "4\n5\n")
    assert verdict == "shed" and n == 2
    assert bucket.shed_rows == 2
    assert any(r[1] == "fh" for r in db.query("SELECT * FROM rw_shed_log"))


def test_copy_defer_waits_outside_session_lock(server):
    """An admission DEFER during COPY must not camp on the shared
    session lock: the deferring producer waits unlocked (TCP
    backpressure to its client) and re-acquires to retry, so other
    sessions' queries keep flowing. Pre-fix, copy_rows slept its whole
    bounded wait (up to 1 s) INSIDE the lock and every other
    connection stalled behind the firehose."""
    import threading
    import time

    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE dw (a BIGINT)")
    g, msgs = _copy(c, "COPY dw FROM STDIN", [b"1\n"])
    assert any(t == b"C" and b.startswith(b"COPY 1") for t, b in msgs)
    db = server.db
    bucket = db._overload.bucket("dw")
    bucket.tokens = 0
    bucket._copy_epoch = db.injector.epoch.curr     # pin: no refill
    done = threading.Event()

    def producer():
        c2 = MiniClient(server.host, server.port)
        c2.startup()
        _g, pm = _copy(c2, "COPY dw FROM STDIN", [b"2\n3\n"])
        done.copied = any(t == b"C" and b.startswith(b"COPY 2")
                          for t, b in pm)
        done.set()

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    time.sleep(0.2)         # let the producer reach its defer loop
    t0 = time.monotonic()
    assert any(tg == b"C" for tg, _ in c.query("SELECT 1"))
    elapsed = time.monotonic() - t0
    assert elapsed < 0.5, (
        f"query stalled {elapsed:.2f}s behind a deferring COPY — the "
        "defer wait is holding the session lock")
    # the deferred COPY itself completes (bounded wait forces the push)
    assert done.wait(10) and done.copied


def test_copy_escapes_and_quoting_edge_cases(server):
    """Review-hardening cases: escaped backslash before t/n/r in text
    format, quoted-empty vs unquoted-empty in csv, and embedded
    delimiters/newlines/doubled quotes inside quoted csv fields —
    including a CopyData boundary landing INSIDE a quoted field."""
    c = MiniClient(server.host, server.port)
    c.startup()
    c.query("CREATE TABLE ce (a BIGINT, s VARCHAR)")
    # text: '\\temp' is escaped-backslash + 'temp', NOT backslash+TAB
    g, msgs = _copy(c, "COPY ce FROM STDIN", [b"1\t\\\\temp\n"])
    assert any(t == b"C" and b.startswith(b"COPY 1") for t, b in msgs)
    # csv: quoted empty = '', unquoted empty = NULL; embedded comma,
    # newline and doubled quote inside quotes; the second CopyData
    # frame starts mid-quoted-field
    g, msgs = _copy(c, "COPY ce FROM STDIN WITH (FORMAT csv)",
                    [b'2,""\n3,\n4,"x,y"\n5,"l1\nl2"\n6,"he said ',
                     b'""hi"""\n'])
    assert any(t == b"C" and b.startswith(b"COPY 5") for t, b in msgs)
    # the '\\.' end-of-data marker is recognized in csv too
    g, msgs = _copy(c, "COPY ce FROM STDIN WITH (FORMAT csv)",
                    [b"7,last\n\\.\n"])
    assert any(t == b"C" and b.startswith(b"COPY 1") for t, b in msgs)
    # multi-statement COPY refuses clearly (0A000), connection usable
    g, msgs = _copy(c, "COPY ce FROM STDIN; SELECT 1", [])
    assert g is None
    err = next(b for t, b in msgs if t == b"E")
    assert b"0A000" in err and b"only statement" in err
    c.query("FLUSH")
    rows = dict(c.rows(c.query("SELECT a, s FROM ce")))
    assert rows == {"1": "\\temp", "2": "", "3": None, "4": "x,y",
                    "5": "l1\nl2", "6": 'he said "hi"', "7": "last"}, rows
