"""ALTER MATERIALIZED VIEW ... SET PARALLELISM end-to-end (VERDICT #10):
SQL-triggered elastic rescale of device-sharded operator state at a
barrier boundary, chaos-style (DML keeps flowing between rescales, kill/
restart replays the DDL log including the ALTER). Reference:
`src/meta/src/stream/scale.rs:2329` + `state_table.rs:694-790`."""
import numpy as np
import pytest

from risingwave_tpu.sql import Database


def _agg_of(db, mv):
    e = db.catalog.get(mv).runtime["shared"].upstream
    stack = [e]
    while stack:
        e = stack.pop()
        if type(e).__name__ == "DeviceHashAggExecutor":
            return e
        for attr in ("input", "port", "left_exec", "right_exec"):
            c = getattr(e, attr, None)
            if c is not None:
                stack.append(c)
    return None


def _oracle(db):
    return sorted(db.query(
        "SELECT k, count(*), sum(v), min(v), max(v) FROM t GROUP BY k"))


def test_alter_parallelism_rescales_device_state():
    db = Database(device=8)
    db.run("CREATE TABLE t (k INT, v BIGINT)")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT k, count(*) AS c, "
           "sum(v) AS s, min(v) AS mn, max(v) AS mx FROM t GROUP BY k")
    rng = np.random.default_rng(3)

    def push():
        rows = ", ".join(f"({int(rng.integers(0, 12))}, "
                         f"{int(rng.integers(-100, 100))})"
                         for _ in range(60))
        db.run(f"INSERT INTO t VALUES {rows}")
        db.run(f"DELETE FROM t WHERE v > {int(rng.integers(50, 90))}")

    push()
    agg = _agg_of(db, "mv")
    assert agg is not None and agg.mesh is not None
    assert agg.mesh.devices.size == 8

    out = db.run("ALTER MATERIALIZED VIEW mv SET PARALLELISM 4")
    assert out == ["ALTER_PARALLELISM_1"]
    assert _agg_of(db, "mv").mesh.devices.size == 4
    push()
    assert sorted(db.query("SELECT * FROM mv")) == _oracle(db)

    # scale in to a single chip and back out, DML between each step
    db.run("ALTER MATERIALIZED VIEW mv SET PARALLELISM 1")
    assert _agg_of(db, "mv").mesh is None
    push()
    assert sorted(db.query("SELECT * FROM mv")) == _oracle(db)

    db.run("ALTER MATERIALIZED VIEW mv SET PARALLELISM 8")
    assert _agg_of(db, "mv").mesh.devices.size == 8
    push()
    assert sorted(db.query("SELECT * FROM mv")) == _oracle(db)
    assert db.catalog.get("mv").parallelism == 8


def test_alter_parallelism_survives_restart(tmp_path):
    """The ALTER is DDL-logged: recovery replays it, and state recovered
    AFTER the replayed rescale loads directly onto the new mesh."""
    d = str(tmp_path)
    db = Database(data_dir=d, device=8)
    db.run("CREATE TABLE t (k INT, v BIGINT)")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT k, sum(v) AS s "
           "FROM t GROUP BY k")
    db.run("INSERT INTO t VALUES (1, 10), (2, 20), (1, 5)")
    db.run("ALTER MATERIALIZED VIEW mv SET PARALLELISM 2")
    db.run("INSERT INTO t VALUES (2, 7), (3, 1)")
    before = sorted(db.query("SELECT * FROM mv"))

    db2 = Database(data_dir=d, device=8)
    assert _agg_of(db2, "mv").mesh.devices.size == 2
    assert sorted(db2.query("SELECT * FROM mv")) == before
    db2.run("DELETE FROM t WHERE v = 20")
    db2.run("INSERT INTO t VALUES (3, 4)")
    assert sorted(db2.query("SELECT * FROM mv")) == sorted(
        db2.query("SELECT k, sum(v) FROM t GROUP BY k"))


def test_chaos_rescale_under_load():
    """Random rescales interleaved with random DML for many rounds; the
    MV must stay exactly equal to the batch oracle throughout (the
    test_chaos_recovery pattern with scale events added)."""
    rng = np.random.default_rng(17)
    db = Database(device=8)
    db.run("CREATE TABLE t (k INT, v BIGINT)")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT k, count(*) AS c, "
           "sum(v) AS s, max(v) AS mx FROM t GROUP BY k")
    sizes = [8, 4, 2, 1]
    for round_no in range(10):
        rows = ", ".join(f"({int(rng.integers(0, 20))}, "
                         f"{int(rng.integers(-50, 50))})"
                         for _ in range(40))
        db.run(f"INSERT INTO t VALUES {rows}")
        if rng.random() < 0.3:
            db.run(f"DELETE FROM t WHERE k = {int(rng.integers(0, 20))}")
        if rng.random() < 0.5:
            n = int(sizes[rng.integers(0, len(sizes))])
            db.run(f"ALTER MATERIALIZED VIEW mv SET PARALLELISM {n}")
        got = sorted(db.query("SELECT * FROM mv"))
        want = sorted(db.query(
            "SELECT k, count(*), sum(v), max(v) FROM t GROUP BY k"))
        assert got == want, f"divergence at round {round_no}"


def test_alter_rescales_device_join():
    """Joins rescale via the re-recovery path (state tables are the
    durable copy; reshard = reload onto the new mesh)."""
    db = Database(device=8)
    db.run("CREATE TABLE a (k INT, x BIGINT)")
    db.run("CREATE TABLE b (k INT, y BIGINT)")
    db.run("CREATE MATERIALIZED VIEW j AS SELECT a.k, a.x, b.y "
           "FROM a JOIN b ON a.k = b.k")
    db.run("INSERT INTO a VALUES (1, 10), (2, 20), (3, 30)")
    db.run("INSERT INTO b VALUES (1, 100), (2, 200), (1, 101)")
    out = db.run("ALTER MATERIALIZED VIEW j SET PARALLELISM 2")
    assert out == ["ALTER_PARALLELISM_1"]
    db.run("INSERT INTO a VALUES (1, 11)")
    db.run("DELETE FROM b WHERE y = 100")
    got = sorted(db.query("SELECT * FROM j"))
    want = sorted(db.query("SELECT a.k, a.x, b.y FROM a JOIN b "
                           "ON a.k = b.k"))
    assert got == want and len(got) > 0


def test_alter_replay_does_not_tick_half_built_dataflow(tmp_path):
    """Regression (review finding): a replayed ALTER must not flush() —
    that ticks sources into only the already-replayed jobs, permanently
    diverging MVs created after the ALTER in the DDL log."""
    d = str(tmp_path)
    total = 600   # bounded source: drains fully, so counts are stable
    db = Database(data_dir=d, device=8)
    db.run("CREATE SOURCE s (v BIGINT) WITH (connector='datagen', "
           f"rows.per.poll='8', datagen.max.rows='{total}')")
    db.run("CREATE MATERIALIZED VIEW m1 AS SELECT v, count(*) AS c "
           "FROM s GROUP BY v")
    db.run("ALTER MATERIALIZED VIEW m1 SET PARALLELISM 2")
    db.run("CREATE MATERIALIZED VIEW m2 AS SELECT count(*) AS c FROM s")
    for _ in range(3):
        db.run("FLUSH")
    n1 = sum(r[1] for r in db.query("SELECT * FROM m1"))
    (n2,) = db.query("SELECT * FROM m2")[0]
    # sources are from-now streams: m2 (created after the ALTER, whose
    # rescale barriers advanced the source) legitimately sees fewer rows
    assert n1 == total and 0 < n2 <= total

    db2 = Database(data_dir=d, device=8)
    m1 = sum(r[1] for r in db2.query("SELECT * FROM m1"))
    (m2,) = db2.query("SELECT * FROM m2")[0]
    # the replay invariant: restart must reproduce EXACTLY the committed
    # counts — a replayed ALTER that ticked would diverge them
    assert m1 == n1 and m2 == n2, (m1, m2, n1, n2)


def test_alter_rejects_non_mv():
    db = Database(device="on")
    db.run("CREATE TABLE t (k INT)")
    with pytest.raises(ValueError, match="not a materialized view"):
        db.run("ALTER MATERIALIZED VIEW t SET PARALLELISM 2")
