"""PR 5 observability plane: epoch-timeline profiler, cluster metrics
plane, worker liveness, and the risectl/ system-table surfaces.

Profiler contract under test (ISSUE 5 acceptance): a fused run yields
rw_epoch_profile rows whose phase splits sum to within 10% of the
measured wall per epoch; the node-stats table attributes rows/occupancy
per node; `risectl profile` prints the offline summary. Plane contract:
after a remote-fragment run, coordinator expose() carries
worker-originated counters, and a wedged (SIGSTOPped, alive) worker
shows in rw_worker_liveness before any spawn/drain deadline."""
import json
import os
import signal
import threading
import time

import pytest

from risingwave_tpu.config import DeviceConfig, ROBUSTNESS
from risingwave_tpu.sql import Database

N = 5_000
CHUNK = 32

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           " nexmark.table='bid', nexmark.max.events='{n}',"
           " nexmark.chunk.size='{c}')")
Q4 = ("CREATE MATERIALIZED VIEW q4 AS SELECT auction, count(*) AS c,"
      " sum(price) AS s, max(price) AS m FROM bid GROUP BY auction")


def drive(db, n=N, chunk=CHUNK):
    for _ in range(n // (64 * chunk) + 3):
        db.tick()


def _fused_db(data_dir=None, profile=True):
    # aot_compile=False pins the INLINE compile lifecycle these tests
    # assert (synchronous compile events on the epoch loop); the AOT
    # service's async event contract is covered by
    # tests/test_compile_service.py
    db = Database(device=DeviceConfig(capacity=512, profile=profile,
                                      aot_compile=False),
                  data_dir=data_dir)
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    assert (db.catalog.get("q4").runtime or {}).get("fused_job") is not None
    drive(db)
    db._fused["q4"].sync()
    return db


# ---------------------------------------------------------------------------
# epoch-timeline profiler
# ---------------------------------------------------------------------------


def test_epoch_profile_rows_and_phase_sums(tmp_path):
    db = _fused_db(str(tmp_path / "d"))
    rows = db.query("SELECT * FROM rw_epoch_profile")
    assert rows, "a fused run must produce epoch profile rows"
    for job, seq, events, shards, hp, h2d, pro, disp, exch, sync, dem, \
            commit, wall in rows:
        assert job == "q4"
        assert shards == 1 and exch == 0.0   # single-chip job
        assert h2d == 0.0                    # no staged ingest transfers
        assert pro == 0.0 and dem == 0.0     # tiering off in tier-1
        phases = hp + h2d + pro + disp + exch + sync + dem + commit
        # phase splits must account for the measured wall (the acceptance
        # bound is 10%; sub-ms epochs get an epsilon for timer noise)
        assert phases <= wall * 1.001 + 0.05
        if wall > 1.0:
            assert phases >= wall * 0.9
    # dispatched epochs carry the epoch's event budget
    assert any(r[2] == 64 * CHUNK for r in rows)
    # warmup is decomposable: the cold compiles were recorded and labeled
    prof = db._fused["q4"].profiler
    assert prof.compiles, "cold per-node compiles must be recorded"
    kinds = {k for _l, k, _s in prof.compiles}
    assert "compile" in kinds
    for label, _k, _s in prof.compiles:
        idx, tname, sig = label.split(":")
        assert tname.endswith("Node") and len(sig) == 8


def test_fused_node_stats_table(tmp_path):
    db = _fused_db(str(tmp_path / "d"))
    rows = db.query("SELECT * FROM rw_fused_node_stats")
    by_type = {r[2]: r for r in rows}
    assert "AggNode" in by_type and "MVKeyedNode" in by_type
    # the source chain generated every bid event exactly once
    chain = by_type["ChainNode"]
    n_bids = chain[5]
    assert 0 < n_bids <= N
    # agg consumed what the chain produced; occupancy = entries/capacity
    agg = by_type["AggNode"]
    assert agg[4] == n_bids                      # rows_in
    assert agg[3] == "main" and agg[7] == 512    # slot, capacity
    assert 0 < agg[8] <= 1.0 and agg[10] is False
    # HBM gauges rode along
    from risingwave_tpu.utils.metrics import REGISTRY
    text = REGISTRY.expose()
    assert 'rw_hbm_bytes{job="q4"' in text
    assert 'rw_hbm_budget_utilization{job="q4",shards="1"}' in text


def test_profile_file_and_risectl(tmp_path, capsys):
    d = str(tmp_path / "d")
    _fused_db(d)
    from risingwave_tpu.utils.profile import PROFILE_FILE
    assert os.path.exists(os.path.join(d, PROFILE_FILE))
    from risingwave_tpu import ctl
    assert ctl.main(["profile", "q4", "--data-dir", d, "--top", "3"]) == 0
    out = json.loads(capsys.readouterr().out)
    assert out["q4"]["epochs"] >= 1
    assert set(out["q4"]["phase_ms"]) >= {"pack", "dispatch",
                                          "device_sync", "commit"}
    assert out["q4"]["slowest_epochs"]
    assert len(out["q4"]["slowest_epochs"]) <= 3
    # unknown job: explicit failure, not an empty report
    assert ctl.main(["profile", "nope", "--data-dir", d]) == 1
    capsys.readouterr()


def test_profile_off_keeps_surfaces_empty():
    db = _fused_db(profile=False)
    assert db.query("SELECT * FROM rw_epoch_profile") == []
    assert db._fused["q4"].profiler.compiles.__len__() == 0
    # results are identical either way (profiling must not affect data)
    assert len(db.query("SELECT * FROM q4")) > 0
    # node attribution stays truthful with the profiler off: the stats
    # vector is pulled at every sync regardless of the profile flag
    rows = db.query("SELECT * FROM rw_fused_node_stats")
    agg = next(r for r in rows if r[2] == "AggNode")
    assert agg[4] > 0 and agg[6] > 0      # rows_in, entries


# ---------------------------------------------------------------------------
# cluster metrics plane + liveness
# ---------------------------------------------------------------------------


SRC_REMOTE = BID_SRC
MV_REMOTE = Q4


def _remote_db(n=20_000, chunk=512, k=2):
    db = Database()
    db.run(SRC_REMOTE.format(n=n, c=chunk))
    db.run(f"SET streaming_parallelism = {k}")
    db.run("SET streaming_placement = 'process'")
    db.run(MV_REMOTE)
    return db


def _find_remote(db, name):
    for jname, r in db._remote_sets():
        if jname == name:
            return r
    raise AssertionError("no remote set")


def test_metrics_plane_cluster_expose():
    """Workers piggyback registry deltas on their result streams; the
    coordinator's expose() becomes cluster-wide."""
    from risingwave_tpu.utils.metrics import REGISTRY
    db = _remote_db()
    rfs = _find_remote(db, "q4")
    for _ in range(20_000 // (64 * 512) + 4):
        db.tick()
    rows = db.query("SELECT * FROM q4")
    assert rows
    text = REGISTRY.expose()
    # the registry is process-global: earlier tests may have merged other
    # worker kinds — assert on THIS run's partial-agg workers only
    worker_lines = [l for l in text.splitlines()
                    if l.startswith("worker_epochs_total{")
                    and 'worker="partial' in l]
    assert len(worker_lines) >= 2, text[:500]
    # liveness gauge: one series per worker slot, fresh heartbeats
    live = [l for l in text.splitlines()
            if l.startswith('worker_liveness{job="q4"')]
    assert len(live) >= 2
    assert any('worker="partial0"' in l for l in live)
    assert any('worker="partial1"' in l for l in live)
    # system table agrees
    lrows = db.query("SELECT * FROM rw_worker_liveness")
    assert len(lrows) == 2
    for job, worker, pid, last_epoch, age, state in lrows:
        assert job == "q4" and state == "ok" and pid > 0
    rfs.shutdown()


def _wait_all_ok(db, deadline_s=15.0):
    """Heartbeat frames are stamped by the drain threads asynchronously
    AFTER barrier delivery, and ages go stale between barriers under a
    tiny timeout — so keep ticking (fresh heartbeats) and poll instead
    of asserting at a single instant."""
    end = time.monotonic() + deadline_s
    rows = []
    while time.monotonic() < end:
        db.tick()
        rows = db._worker_liveness_rows()
        if rows and all(r[5] == "ok" for r in rows):
            return rows
        time.sleep(0.02)
    raise AssertionError(f"workers never all 'ok': {rows}")


def test_wedged_worker_detected_by_heartbeat_age():
    """A SIGSTOPped worker is alive-but-stuck: process poll() stays None
    (so the death sweep can't see it), but its heartbeat frames stop —
    rw_worker_liveness must flag it while a tick is still in flight,
    BEFORE any spawn/drain deadline trips.

    The timeout is shrunk ONLY for the stopped phase: heartbeats ride
    result barriers, so under a tiny timeout a healthy-but-slow pipeline
    (warmup ticks on a loaded host) would legitimately read as wedged
    too — the 'ok' baselines run under the default timeout."""
    saved = ROBUSTNESS.heartbeat_timeout_s
    # bounded source sized so the handful of liveness-poll ticks can
    # never drain it (drained workers exit -> 'dead', not 'ok')
    db = _remote_db(n=800_000, chunk=128)
    rfs = _find_remote(db, "q4")
    stopped = []
    try:
        db.tick()                      # healthy baseline, heartbeats flow
        _wait_all_ok(db)
        victim = rfs.workers[0].proc
        os.kill(victim.pid, signal.SIGSTOP)
        stopped.append(victim.pid)
        ROBUSTNESS.heartbeat_timeout_s = 0.4
        # drive ticks from a background thread: with a stopped worker the
        # barrier can't align, so the tick blocks — exactly the situation
        # an operator diagnoses through the liveness surface
        t = threading.Thread(target=lambda: [db.tick() for _ in range(3)],
                             daemon=True)
        t.start()
        deadline = time.monotonic() + 15
        wedged = None
        while time.monotonic() < deadline:
            rows = db._worker_liveness_rows()
            wedged = next((r for r in rows if r[1] == "partial0"
                           and r[5] == "wedged?"), None)
            if wedged is not None:
                break
            time.sleep(0.05)
        assert wedged is not None, rows
        assert victim.poll() is None, "worker must be alive (just stuck)"
        assert wedged[4] > ROBUSTNESS.heartbeat_timeout_s
        os.kill(victim.pid, signal.SIGCONT)
        stopped.clear()
        ROBUSTNESS.heartbeat_timeout_s = saved
        t.join(120)
        assert not t.is_alive(), "ticks must complete after SIGCONT"
        # recovered: heartbeats flow again
        _wait_all_ok(db)
    finally:
        for pid in stopped:
            os.kill(pid, signal.SIGCONT)
        ROBUSTNESS.heartbeat_timeout_s = saved
        rfs.shutdown()


# ---------------------------------------------------------------------------
# trace satellites: --stuck-only + constant-memory rotation
# ---------------------------------------------------------------------------


def test_trace_stuck_only(tmp_path, capsys):
    from risingwave_tpu.utils.trace import BarrierTracer, diagnose
    d = str(tmp_path)
    tr = BarrierTracer(d)
    s1 = tr.inject(1, "checkpoint")
    s1.job_start("mv_ok")
    s1.job_end("mv_ok")
    s1.commit()
    s2 = tr.inject(2, "barrier")
    s2.job_start("mv_stuck")                  # never ends, never commits
    path = os.path.join(d, "barrier_trace.jsonl")
    full = diagnose(path, last=10)
    assert "committed" in full and "OPEN" in full
    stuck = diagnose(path, last=10, stuck_only=True)
    assert "mv_stuck" in stuck and "committed" not in stuck
    # even when committed traffic pushed the stall out of the tail window
    for e in range(3, 40):
        s = tr.inject(e, "checkpoint")
        s.commit()
    assert "mv_stuck" in diagnose(path, last=5, stuck_only=True)
    assert "mv_stuck" not in diagnose(path, last=5)
    # risectl flag wiring
    from risingwave_tpu import ctl
    assert ctl.main(["trace", "--data-dir", d, "--stuck-only"]) == 0
    assert "mv_stuck" in capsys.readouterr().out


def test_rotate_tail_is_line_exact(tmp_path):
    from risingwave_tpu.utils.trace import rotate_tail
    path = str(tmp_path / "log.jsonl")
    with open(path, "w") as f:
        for e in range(10_000):
            f.write(json.dumps({"epoch": e, "pad": "x" * 40}) + "\n")
    before = os.path.getsize(path)
    rotate_tail(path)
    after = os.path.getsize(path)
    assert after <= before // 2 + 64
    with open(path) as f:
        recs = [json.loads(l) for l in f]     # every line intact JSON
    # the tail is contiguous and newest-preserving
    assert recs[-1]["epoch"] == 9_999
    assert recs[0]["epoch"] > 0
    assert [r["epoch"] for r in recs] == list(
        range(recs[0]["epoch"], 10_000))


def test_tracer_emit_rotates(tmp_path, monkeypatch):
    from risingwave_tpu.utils import trace as trace_mod
    monkeypatch.setattr(trace_mod, "_MAX_FILE_BYTES", 1 << 14)
    tr = trace_mod.BarrierTracer(str(tmp_path))
    path = os.path.join(str(tmp_path), trace_mod.TRACE_FILE)
    prev = 0
    shrinks = 0
    for e in range(6_000):        # 2 emits/span -> several rotation checks
        span = tr.inject(e, "barrier")
        span.commit()
        size = os.path.getsize(path)
        if size < prev:
            shrinks += 1
        prev = size
    # rotation fired (the file shrank mid-run) and the survivors are
    # intact JSON lines ending at the newest event
    assert shrinks >= 1
    with open(path) as f:
        recs = [json.loads(l) for l in f]
    assert recs[-1]["epoch"] == 5_999 and recs[0]["epoch"] > 0


# ---------------------------------------------------------------------------
# timer-driven worker-side heartbeat (ISSUE 6 satellite: coordinator-
# quiescent periods — long AOT compiles, paused injectors — must not
# read as a wedged worker)
# ---------------------------------------------------------------------------


def test_heartbeat_timer_fires_during_quiet_window():
    from risingwave_tpu.runtime.worker import HeartbeatTimer
    sends = []
    t = HeartbeatTimer(lambda e: sends.append((time.monotonic(), e)),
                       period=0.05)
    t.start()
    try:
        time.sleep(0.3)
        assert len(sends) >= 2, \
            "a quiet worker must keep emitting timer heartbeats"
    finally:
        t.stop()
    n = len(sends)
    time.sleep(0.15)
    assert len(sends) == n, "stop() must halt the timer"


def test_heartbeat_timer_suppressed_by_traffic():
    """While barrier-piggybacked heartbeats flow (mark()), the timer
    stays silent — no duplicate frames on a healthy stream."""
    from risingwave_tpu.runtime.worker import HeartbeatTimer
    sends = []
    t = HeartbeatTimer(lambda e: sends.append(e), period=0.2)
    t.start()
    try:
        end = time.monotonic() + 0.6
        while time.monotonic() < end:
            t.mark(epoch=7)
            time.sleep(0.02)
        assert sends == [], "traffic-proven liveness must hold the timer"
    finally:
        t.stop()


def test_heartbeat_timer_default_period_tracks_timeout():
    from risingwave_tpu.runtime.worker import HeartbeatTimer
    t = HeartbeatTimer(lambda e: None)
    assert 0 < t.period < ROBUSTNESS.heartbeat_timeout_s, \
        "the fallback must beat faster than the wedged threshold"
