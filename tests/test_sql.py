"""SQL frontend end-to-end: DDL, DML, MV maintenance, batch queries.

The oracle everywhere: MV contents == batch recompute over the same data
(the reference's sqllogictest-driven MV/batch equivalence, SURVEY.md §4).
"""
from decimal import Decimal

import pytest

from risingwave_tpu.sql import Database


@pytest.fixture()
def db():
    return Database()


class TestBasics:
    def test_create_insert_select(self, db):
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("INSERT INTO t VALUES (1, 10), (2, 20), (3, NULL)")
        assert sorted(db.query("SELECT k, v FROM t")) == \
            [(1, 10), (2, 20), (3, None)]

    def test_where_and_exprs(self, db):
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("INSERT INTO t VALUES (1, 10), (2, 20), (3, 30)")
        assert db.query("SELECT k + v FROM t WHERE v > 15 AND k < 3") == \
            [(22,)]
        assert sorted(db.query(
            "SELECT k FROM t WHERE v BETWEEN 10 AND 20")) == [(1,), (2,)]
        assert db.query("SELECT k FROM t WHERE k IN (3, 4)") == [(3,)]

    def test_case_cast_extract(self, db):
        db.run("CREATE TABLE t (ts TIMESTAMP, v BIGINT)")
        db.run("INSERT INTO t VALUES ('2026-07-29 10:30:00', 7)")
        assert db.query(
            "SELECT extract(year FROM ts), CAST(v AS DOUBLE), "
            "CASE WHEN v > 5 THEN 'hi' ELSE 'lo' END FROM t") == \
            [(2026, 7.0, "hi")]

    def test_delete(self, db):
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("INSERT INTO t VALUES (1, 10), (2, 20)")
        db.run("DELETE FROM t WHERE k = 1")
        assert db.query("SELECT k FROM t") == [(2,)]

    def test_primary_key_upsert(self, db):
        db.run("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        db.run("INSERT INTO t VALUES (1, 10)")
        db.run("INSERT INTO t VALUES (1, 99)")
        assert db.query("SELECT v FROM t") == [(99,)]

    def test_show_and_drop(self, db):
        db.run("CREATE TABLE t (k BIGINT)")
        assert db.run("SHOW TABLES")[0] == ["t"]
        db.run("DROP TABLE t")
        assert db.run("SHOW TABLES")[0] == []


class TestMVMaintenance:
    def test_agg_mv_incremental(self, db):
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("CREATE MATERIALIZED VIEW m AS "
               "SELECT k, count(*) AS c, sum(v) AS s FROM t GROUP BY k")
        db.run("INSERT INTO t VALUES (1, 10), (1, 20), (2, 5)")
        assert sorted(db.query("SELECT * FROM m")) == \
            [(1, 2, Decimal(30)), (2, 1, Decimal(5))]
        db.run("DELETE FROM t WHERE v = 20")
        assert sorted(db.query("SELECT * FROM m")) == \
            [(1, 1, Decimal(10)), (2, 1, Decimal(5))]
        db.run("DELETE FROM t WHERE k = 2")
        assert db.query("SELECT * FROM m") == [(1, 1, Decimal(10))]

    def test_mv_on_mv_backfill(self, db):
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("INSERT INTO t VALUES (1, 10), (2, 20)")  # data BEFORE the MV
        db.run("CREATE MATERIALIZED VIEW m1 AS "
               "SELECT k, sum(v) AS s FROM t GROUP BY k")
        db.run("CREATE MATERIALIZED VIEW m2 AS "
               "SELECT count(*) AS n FROM m1")
        db.run("FLUSH")
        assert db.query("SELECT n FROM m2") == [(2,)]
        db.run("INSERT INTO t VALUES (3, 30)")
        assert db.query("SELECT n FROM m2") == [(3,)]

    def test_join_mv(self, db):
        db.run("CREATE TABLE a (id BIGINT PRIMARY KEY, cat BIGINT)")
        db.run("CREATE TABLE b (aid BIGINT, price BIGINT)")
        db.run("CREATE MATERIALIZED VIEW j AS SELECT a.cat, b.price "
               "FROM b JOIN a ON b.aid = a.id")
        db.run("INSERT INTO a VALUES (1, 100)")
        db.run("INSERT INTO b VALUES (1, 5), (1, 7), (2, 9)")
        assert sorted(db.query("SELECT * FROM j")) == [(100, 5), (100, 7)]
        db.run("INSERT INTO a VALUES (2, 200)")
        assert sorted(db.query("SELECT * FROM j")) == \
            [(100, 5), (100, 7), (200, 9)]

    def test_left_join_null_padding(self, db):
        db.run("CREATE TABLE a (id BIGINT PRIMARY KEY, x BIGINT)")
        db.run("CREATE TABLE b (id BIGINT PRIMARY KEY, y BIGINT)")
        db.run("CREATE MATERIALIZED VIEW j AS SELECT a.x, b.y "
               "FROM a LEFT JOIN b ON a.id = b.id")
        db.run("INSERT INTO a VALUES (1, 10)")
        assert db.query("SELECT * FROM j") == [(10, None)]
        db.run("INSERT INTO b VALUES (1, 99)")
        assert db.query("SELECT * FROM j") == [(10, 99)]

    def test_topn_mv(self, db):
        db.run("CREATE TABLE t (v BIGINT)")
        db.run("CREATE MATERIALIZED VIEW top2 AS "
               "SELECT v FROM t ORDER BY v DESC LIMIT 2")
        db.run("INSERT INTO t VALUES (5), (1), (9), (3)")
        assert sorted(db.query("SELECT v FROM top2")) == [(5,), (9,)]
        db.run("DELETE FROM t WHERE v = 9")
        assert sorted(db.query("SELECT v FROM top2")) == [(3,), (5,)]

    def test_tumble_window_mv(self, db):
        db.run("CREATE TABLE ev (ts TIMESTAMP, v BIGINT)")
        db.run("CREATE MATERIALIZED VIEW w AS SELECT window_start, "
               "count(*) AS c FROM TUMBLE(ev, ts, INTERVAL '10' SECOND) "
               "GROUP BY window_start")
        db.run("INSERT INTO ev VALUES ('2026-01-01 00:00:01', 1), "
               "('2026-01-01 00:00:05', 2), ('2026-01-01 00:00:12', 3)")
        rows = sorted(db.query("SELECT * FROM w"))
        assert [c for _, c in rows] == [2, 1]

    def test_simple_agg_no_group(self, db):
        db.run("CREATE TABLE t (v BIGINT)")
        db.run("CREATE MATERIALIZED VIEW m AS SELECT count(*) AS c, "
               "min(v) AS mn FROM t")
        db.run("FLUSH")
        assert db.query("SELECT * FROM m") == [(0, None)]
        db.run("INSERT INTO t VALUES (5), (2)")
        assert db.query("SELECT * FROM m") == [(2, 2)]

    def test_having(self, db):
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("CREATE MATERIALIZED VIEW m AS SELECT k, count(*) AS c "
               "FROM t GROUP BY k HAVING count(*) > 1")
        db.run("INSERT INTO t VALUES (1, 1), (1, 2), (2, 3)")
        assert db.query("SELECT * FROM m") == [(1, 2)]

    def test_distinct(self, db):
        db.run("CREATE TABLE t (k BIGINT)")
        db.run("INSERT INTO t VALUES (1), (1), (2)")
        assert sorted(db.query("SELECT DISTINCT k FROM t")) == [(1,), (2,)]

    def test_sink_collects_changes(self, db):
        db.run("CREATE TABLE t (k BIGINT)")
        db.run("CREATE SINK s FROM t WITH (connector='blackhole')")
        db.run("INSERT INTO t VALUES (1), (2)")
        assert len(db.sink_results["s"]) == 2


class TestBatchOrderLimit:
    def test_order_by_limit(self, db):
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("INSERT INTO t VALUES (1, 30), (2, 10), (3, 20)")
        assert db.query("SELECT k, v FROM t ORDER BY v DESC LIMIT 2") == \
            [(1, 30), (3, 20)]
        assert db.query("SELECT k FROM t ORDER BY v ASC LIMIT 1 OFFSET 1") \
            == [(3,)]


class TestNexmarkSource:
    def test_bid_source_counts(self, db):
        db.run("CREATE SOURCE nbid (auction BIGINT, bidder BIGINT, "
               "price BIGINT, channel VARCHAR, url VARCHAR, "
               "date_time TIMESTAMP, extra VARCHAR) WITH ("
               "connector='nexmark', nexmark.table='bid', "
               "nexmark.max.events='500')")
        db.run("CREATE MATERIALIZED VIEW c AS SELECT count(*) AS n FROM nbid")
        db.run("FLUSH")
        db.run("FLUSH")
        (n,), = db.query("SELECT n FROM c")
        assert n > 400  # ~92% of nexmark events are bids


class TestDurability:
    def test_database_over_spill_store(self, tmp_path):
        d = str(tmp_path)
        db = Database(data_dir=d)
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("INSERT INTO t VALUES (1, 10), (2, 20)")
        del db
        db2 = Database(data_dir=d)  # DDL log replays the catalog
        assert sorted(db2.query("SELECT k, v FROM t")) == [(1, 10), (2, 20)]

    def test_full_recovery_with_mv_and_agg_state(self, tmp_path):
        d = str(tmp_path)
        db = Database(data_dir=d)
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("CREATE MATERIALIZED VIEW m AS "
               "SELECT k, count(*) AS c, sum(v) AS s FROM t GROUP BY k")
        db.run("INSERT INTO t VALUES (1, 10), (1, 20), (2, 5)")
        before = sorted(db.query("SELECT * FROM m"))
        del db

        db2 = Database(data_dir=d)
        assert sorted(db2.query("SELECT * FROM m")) == before
        # incremental maintenance continues from recovered agg state
        db2.run("INSERT INTO t VALUES (1, 100)")
        from decimal import Decimal
        assert sorted(db2.query("SELECT * FROM m")) == \
            [(1, 3, Decimal(130)), (2, 1, Decimal(5))]
        db2.run("DELETE FROM t WHERE k = 2")
        assert db2.query("SELECT * FROM m") == [(1, 3, Decimal(130))]

    def test_recovery_drop_replay(self, tmp_path):
        d = str(tmp_path)
        db = Database(data_dir=d)
        db.run("CREATE TABLE t (k BIGINT)")
        db.run("CREATE TABLE u (k BIGINT)")
        db.run("DROP TABLE u")
        del db
        db2 = Database(data_dir=d)
        assert db2.run("SHOW TABLES")[0] == ["t"]


class TestUpdate:
    def test_update_propagates_to_mv(self, db):
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("CREATE MATERIALIZED VIEW m AS "
               "SELECT k, sum(v) AS s FROM t GROUP BY k")
        db.run("INSERT INTO t VALUES (1, 10), (1, 20), (2, 5)")
        db.run("UPDATE t SET v = v + 100 WHERE k = 1")
        assert sorted(db.query("SELECT k, v FROM t")) == \
            [(1, 110), (1, 120), (2, 5)]
        from decimal import Decimal
        assert sorted(db.query("SELECT * FROM m")) == \
            [(1, Decimal(230)), (2, Decimal(5))]

    def test_update_pk_table(self, db):
        db.run("CREATE TABLE t (k BIGINT PRIMARY KEY, v BIGINT)")
        db.run("INSERT INTO t VALUES (1, 10), (2, 20)")
        db.run("UPDATE t SET v = 99 WHERE k = 2")
        assert sorted(db.query("SELECT * FROM t")) == [(1, 10), (2, 99)]

    def test_update_no_match(self, db):
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("INSERT INTO t VALUES (1, 10)")
        assert db.run("UPDATE t SET v = 5 WHERE k = 42")[0] == "UPDATE_0"
