"""risectl-lite (`python -m risingwave_tpu.ctl`) against a live data dir
(`src/ctl/src/cmd_impl/` analog)."""
import json

from risingwave_tpu import ctl
from risingwave_tpu.sql import Database


def _mk_db(d):
    db = Database(data_dir=d)
    db.run("CREATE TABLE t (k INT, v INT)")
    db.run("CREATE MATERIALIZED VIEW mv AS SELECT k, count(*) AS c "
           "FROM t GROUP BY k")
    db.run("INSERT INTO t VALUES (1, 10), (2, 20), (1, 30)")
    db.run("FLUSH")
    db.store.close()
    return db


def test_jobs_and_ddl_log(tmp_path, capsys):
    _mk_db(str(tmp_path))
    assert ctl.main(["jobs", "--data-dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "TABLE" in out and "t" in out
    assert "MATERIALIZED VIEW" in out and "mv" in out
    assert ctl.main(["ddl-log", "--data-dir", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "create table t" in out.lower()


def test_manifest_and_compact(tmp_path, capsys):
    d = str(tmp_path)
    db = _mk_db(d)
    # more commits -> more runs to compact
    for i in range(3):
        db.run(f"INSERT INTO t VALUES ({i + 10}, {i})")
        db.run("FLUSH")
    db.store.close()
    assert ctl.main(["manifest", "--data-dir", d]) == 0
    m = json.loads(capsys.readouterr().out)
    assert m["committed_epoch"] > 0 and m["tables"]
    total_runs = sum(len(t["runs"]) for t in m["tables"].values())
    assert ctl.main(["compact", "--data-dir", d]) == 0
    capsys.readouterr()
    assert ctl.main(["manifest", "--data-dir", d]) == 0
    m2 = json.loads(capsys.readouterr().out)
    total2 = sum(len(t["runs"]) for t in m2["tables"].values())
    assert total2 <= total_runs
    assert all(len(t["runs"]) <= 1 for t in m2["tables"].values())
    # data survives compaction: reopen and read the MV
    db2 = Database(data_dir=d)
    rows = dict(db2.query("SELECT * FROM mv"))
    assert rows[1] == 2 and rows[2] == 1


def test_dump(tmp_path, capsys):
    d = str(tmp_path)
    _mk_db(d)
    assert ctl.main(["dump", "mv", "--data-dir", d]) == 0
    out = capsys.readouterr().out
    assert out.splitlines()[0].startswith("k\tc")
    assert "-- 2 rows" in out
    assert ctl.main(["dump", "t", "--data-dir", d, "--limit", "1"]) == 0
    out = capsys.readouterr().out
    assert "more)" in out


def test_metrics_read_only(tmp_path, capsys):
    d = str(tmp_path)
    db = _mk_db(d)
    epoch_before = db.store.committed_epoch
    assert ctl.main(["metrics", "--data-dir", d]) == 0
    out = capsys.readouterr().out
    assert "committed_epoch" in out
    # a diagnostic must not advance durable state
    from risingwave_tpu.state import SpillStateStore
    assert SpillStateStore(d).committed_epoch == epoch_before


def test_dir_lock_refuses_second_process(tmp_path):
    """Cross-process single-owner invariant: a second PROCESS opening the
    same data dir fails fast (ctl against a live server)."""
    import subprocess, sys, os
    d = str(tmp_path)
    _mk_db(d)
    code = ("import sys; sys.path.insert(0, %r); "
            "import jax; jax.config.update('jax_platforms', 'cpu'); "
            "from risingwave_tpu.state import SpillStateStore; "
            "SpillStateStore(%r)") % (os.getcwd(), d)
    # in-process reopen is fine (recovery-test pattern)...
    from risingwave_tpu.state import SpillStateStore
    SpillStateStore(d)
    # ...but another process must be refused while this one holds the lock
    r = subprocess.run([sys.executable, "-c", code],
                       capture_output=True, text=True)
    assert r.returncode != 0 and "locked by another process" in r.stderr
