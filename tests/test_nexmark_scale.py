"""Scale e2e (VERDICT weak #9): the SQL stack driven with tens of
thousands of generated Nexmark events, verified against independent
numpy oracles — the bench.py workloads as tests, host and device paths.
Multi-epoch on purpose (epoch boundaries found the join-netting bug)."""
import numpy as np
import pytest

from risingwave_tpu.connectors.nexmark import NexmarkGenerator
from risingwave_tpu.sql import Database

N_EVENTS = 40_960
CHUNK = 512          # 64-chunk epochs -> ~1.25 epochs per tick

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           f" nexmark.table='bid', nexmark.max.events='{N_EVENTS}',"
           f" nexmark.chunk.size='{CHUNK}')")

USEC = 1_000_000


def _drive(db):
    for _ in range(N_EVENTS // (64 * CHUNK) + 3):
        db.tick()


def _bid_cols():
    ch = NexmarkGenerator().gen_range(0, N_EVENTS)["bid"]
    return (ch.columns[0].values.astype(np.int64),
            ch.columns[2].values.astype(np.int64),
            ch.columns[5].values.astype(np.int64))


@pytest.mark.parametrize("device", ["off", "on"])
def test_q4_agg_at_scale(device):
    auction, price, _ts = _bid_cols()
    order = np.argsort(auction, kind="stable")
    a = auction[order]
    p = price[order]
    bounds = np.flatnonzero(np.r_[True, a[1:] != a[:-1]])
    oracle = {
        int(k): (int(c), int(s), int(m))
        for k, c, s, m in zip(a[bounds],
                              np.diff(np.r_[bounds, len(a)]),
                              np.add.reduceat(p, bounds),
                              np.maximum.reduceat(p, bounds))}
    db = Database(device=device)
    db.run(BID_SRC)
    db.run("CREATE MATERIALIZED VIEW q4 AS SELECT auction, count(*) AS c, "
           "sum(price) AS s, max(price) AS m FROM bid GROUP BY auction")
    _drive(db)
    rows = db.query("SELECT * FROM q4")
    assert len(rows) == len(oracle) > 500
    for a_, c, s, m in rows:
        assert oracle[int(a_)] == (int(c), int(s), int(m))


@pytest.mark.parametrize("device", ["off", "on"])
def test_q7_core_window_max_at_scale(device):
    _auction, price, ts = _bid_cols()
    size = 10 * USEC
    wend = (ts // size) * size + size
    order = np.argsort(wend, kind="stable")
    w = wend[order]
    p = price[order]
    bounds = np.flatnonzero(np.r_[True, w[1:] != w[:-1]])
    oracle = sorted((int(k), int(m)) for k, m in
                    zip(w[bounds], np.maximum.reduceat(p, bounds)))
    db = Database(device=device)
    db.run(BID_SRC)
    db.run("CREATE MATERIALIZED VIEW q7m AS SELECT window_end AS we, "
           "max(price) AS mp FROM TUMBLE(bid, date_time, "
           "INTERVAL '10' SECOND) GROUP BY window_end")
    _drive(db)
    assert sorted((int(a), int(b))
                  for a, b in db.query("SELECT * FROM q7m")) == oracle


@pytest.mark.parametrize("device", ["off", "on"])
def test_q5_full_at_scale(device):
    """The full reference q5 (hop windows, nested max, self-join with a
    non-equi condition) — the query that exposed cross-delta pair
    resurrection."""
    auction, _price, ts = _bid_cols()
    hop, size = 2 * USEC, 10 * USEC
    n = size // hop
    first = (ts // hop) * hop
    ws = (first[:, None] - (np.arange(n) * hop)[None, :]).reshape(-1)
    au = np.repeat(auction, n)
    wn = (ws - ws.min()) // hop
    comp = wn * np.int64(1 << 32) + au
    order = np.argsort(comp, kind="stable")
    ck = comp[order]
    bounds = np.flatnonzero(np.r_[True, ck[1:] != ck[:-1]])
    num = np.diff(np.r_[bounds, len(ck)])
    kws, kau = ck[bounds] >> 32, ck[bounds] & ((1 << 32) - 1)
    oracle = []
    for wv in np.unique(kws):
        sel = kws == wv
        mx = num[sel].max()
        for a_, c in zip(kau[sel][num[sel] >= mx], num[sel][num[sel] >= mx]):
            oracle.append((int(a_), int(c)))
    oracle.sort()

    db = Database(device=device)
    db.run(BID_SRC)
    db.run("""CREATE MATERIALIZED VIEW q5 AS
SELECT AuctionBids.auction, AuctionBids.num FROM (
    SELECT bid.auction, count(*) AS num, window_start AS starttime
    FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
    GROUP BY window_start, bid.auction
) AS AuctionBids
JOIN (
    SELECT max(CountBids.num) AS maxn, CountBids.starttime_c
    FROM (
        SELECT count(*) AS num, window_start AS starttime_c
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY bid.auction, window_start
    ) AS CountBids
    GROUP BY CountBids.starttime_c
) AS MaxBids
ON AuctionBids.starttime = MaxBids.starttime_c
   AND AuctionBids.num >= MaxBids.maxn""")
    _drive(db)
    got = sorted((int(a), int(c))
                 for a, c in db.query("SELECT * FROM q5"))
    assert got == oracle and len(got) > 0
