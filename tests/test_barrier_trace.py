"""Barrier trace: rw_barrier_trace system table + risectl trace hang
localization (monitor_service.rs:82 await-tree / tracing.rs:45
TracingContext analog)."""
import json
import os

from risingwave_tpu.sql import Database


def test_rw_barrier_trace_rows():
    db = Database()
    db.run("CREATE TABLE t (v BIGINT)")
    db.run("INSERT INTO t VALUES (1), (2)")
    db.run("CREATE MATERIALIZED VIEW m AS SELECT count(*) AS c FROM t")
    db.tick()
    db.tick()
    rows = db.query("SELECT * FROM rw_barrier_trace")
    assert rows, "trace must record barriers"
    # every barrier committed; the per-job spans are done
    states = {r[3] for r in rows}
    assert "committed" in states
    assert "OPEN" not in states and "RUNNING" not in states
    jobs = {r[2] for r in rows}
    assert "m" in jobs and "<barrier>" in jobs


def test_trace_file_localizes_hang(tmp_path):
    """A job that never finishes collecting leaves a durable
    collect_start with no end — `risectl trace` names it."""
    d = str(tmp_path / "data")
    db = Database(data_dir=d)
    db.run("CREATE TABLE t (v BIGINT)")
    db.run("CREATE MATERIALIZED VIEW m AS SELECT count(*) AS c FROM t")
    db.tick()

    # simulate the r03-style wedge: inject + start collecting job 'm',
    # then the process dies before collect_end/commit
    span = db.tracer.inject(999, "checkpoint")
    span.job_start("m")

    from risingwave_tpu.ctl import main as ctl_main
    import io
    import contextlib
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = ctl_main(["trace", "--data-dir", d])
    out = buf.getvalue()
    assert rc == 0
    assert "epoch 999" in out and "OPEN" in out and "m" in out, out
    # the healthy epoch reads committed
    assert "committed" in out


def test_trace_survives_without_data_dir():
    db = Database()          # memory store: ring only, no file
    db.run("CREATE TABLE t (v BIGINT)")
    db.tick()
    assert db.tracer.rows()
