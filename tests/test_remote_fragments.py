"""SQL-driven multi-process fragments: 2-phase agg across worker OS
processes over the credit-flow exchange.

Reference analogs: plan → fragments → actors on compute nodes
(`src/meta/src/stream/stream_manager.rs:254`,
`src/stream/src/task/stream_manager.rs:610`), the 2-phase aggregation
rewrite (partial agg + sum0 merge), and worker-failure recovery via job
restart (`src/meta/src/barrier/worker.rs:664`).
"""
import os
import signal
import time

import pytest

from risingwave_tpu.sql import Database

SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
       " channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR)"
       " WITH (connector='nexmark', nexmark.table='bid',"
       " nexmark.max.events='{n}', nexmark.chunk.size='{c}')")
MV = ("CREATE MATERIALIZED VIEW q4 AS SELECT auction, count(*) AS c,"
      " sum(price) AS s, max(price) AS m FROM bid GROUP BY auction")


def drive(db, n, chunk):
    for _ in range(n // (64 * chunk) + 4):
        db.tick()


def find_remote(db, name):
    """Walk the MV's executor tree to its RemoteFragmentSet."""
    obj = db.catalog.get(name)
    stack = [obj.runtime["shared"].upstream]
    while stack:
        e = stack.pop()
        r = getattr(e, "_remote", None)
        if r is not None:
            return r
        for attr in ("input", "left_exec", "right_exec"):
            c = getattr(e, attr, None)
            if c is not None:
                stack.append(c)
    raise AssertionError("no RemoteFragmentSet in the plan")


def host_oracle(n, chunk):
    db = Database()
    db.run(SRC.format(n=n, c=chunk))
    db.run(MV)
    drive(db, n, chunk)
    return sorted(db.query("SELECT * FROM q4"))


def test_two_process_q4_matches_single_process():
    n, chunk = 20_000, 512
    db = Database()
    db.run(SRC.format(n=n, c=chunk))
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement = 'process'")
    db.run(MV)
    rfs = find_remote(db, "q4")
    assert len(rfs.workers) == 2
    assert all(w.proc.poll() is None for w in rfs.workers), \
        "both workers must be live OS processes"
    drive(db, n, chunk)
    got = sorted(db.query("SELECT * FROM q4"))
    assert got == host_oracle(n, chunk)
    rfs.shutdown()


def test_worker_kill_detected_and_recovered(tmp_path):
    """Kill one worker mid-stream: the coordinator must DETECT it (raise,
    not hang), the uncommitted epoch must vanish, and a restarted process
    (DDL replay, fresh stateless workers, committed source offsets) must
    converge to the exact result."""
    from risingwave_tpu.runtime.remote_fragments import RemoteWorkerDied
    n, chunk = 40_000, 256
    d = str(tmp_path / "data")
    db = Database(data_dir=d)
    db.run(SRC.format(n=n, c=chunk))
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement = 'process'")
    db.run(MV)
    for _ in range(3):
        db.tick()
    rfs = find_remote(db, "q4")
    rfs.workers[0].proc.kill()
    with pytest.raises(RemoteWorkerDied):
        for _ in range(10):
            db.tick()
    rfs.shutdown()
    del db
    db2 = Database(data_dir=d)
    rfs2 = find_remote(db2, "q4")
    assert all(w.proc.poll() is None for w in rfs2.workers), \
        "recovery must respawn fresh workers"
    drive(db2, n, chunk)
    assert sorted(db2.query("SELECT * FROM q4")) == host_oracle(n, chunk)
    rfs2.shutdown()


@pytest.mark.slow
def test_process_placement_wall_clock_overhead_bounded():
    """Process placement moves the per-row aggregation into worker CPUs,
    but the COORDINATOR still runs the source + dispatch + final merge in
    Python — Amdahl's serial fraction. Until sources themselves place
    into workers (split ownership, like the reference's per-actor source
    splits), the honest claim is bounded overhead, not speedup: the
    4-process run must stay within 2x of single-fragment wall clock on
    the same workload while producing identical results. (Profiling notes:
    the serial floor is datagen + vnode dispatch + wire encode; worker CPU
    utilization confirms the fragments themselves do scale.)"""
    n, chunk = 160_000, 1024

    def run(parallel):
        db = Database()
        db.run(SRC.format(n=n, c=chunk))
        if parallel:
            db.run("SET streaming_parallelism = 4")
            db.run("SET streaming_placement = 'process'")
        db.run(MV)
        if parallel:
            find_remote(db, "q4")     # assert placement actually happened
        t0 = time.perf_counter()
        drive(db, n, chunk)
        dt = time.perf_counter() - t0
        rows = sorted(db.query("SELECT * FROM q4"))
        return dt, rows

    t1, rows1 = run(False)
    tk, rowsk = run(True)
    assert rowsk == rows1
    assert tk < t1 * 2.0, (t1, tk)


JOIN_MV = ("CREATE MATERIALIZED VIEW rj AS SELECT a.v, b.w"
           " FROM a JOIN b ON a.k = b.k")


def _join_db(d=None, outer=False):
    db = Database(data_dir=d) if d else Database()
    db.run("CREATE TABLE a (k BIGINT, v BIGINT)")
    db.run("CREATE TABLE b (k BIGINT, w BIGINT)")
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement = 'process'")
    db.run(JOIN_MV.replace("JOIN", "LEFT JOIN") if outer else JOIN_MV)
    return db


class TestRemoteJoin:
    """Hash joins across worker OS processes (RemoteStatefulSet): every
    fragment type places on compute nodes (`stream_manager.rs:254`)."""

    def test_inner_join_with_retraction(self):
        db = _join_db()
        rfs = find_remote(db, "rj")
        assert len(rfs.workers) == 2 \
            and all(w.proc.poll() is None for w in rfs.workers)
        db.run("INSERT INTO a VALUES (1, 10), (2, 20), (3, 30)")
        db.run("INSERT INTO b VALUES (1, 100), (2, 200)")
        for _ in range(4):
            db.tick()
        assert sorted(db.query("SELECT * FROM rj")) == \
            [(10, 100), (20, 200)]
        db.run("DELETE FROM b WHERE k = 1")
        for _ in range(4):
            db.tick()
        assert sorted(db.query("SELECT * FROM rj")) == [(20, 200)]
        rfs.shutdown()

    def test_left_outer_join_remote(self):
        db = _join_db(outer=True)
        db.run("INSERT INTO a VALUES (1, 10), (9, 90)")
        db.run("INSERT INTO b VALUES (1, 100)")
        for _ in range(4):
            db.tick()
        assert sorted(db.query("SELECT * FROM rj"),
                      key=lambda r: (r[0],)) == [(10, 100), (90, None)]
        find_remote(db, "rj").shutdown()

    def test_worker_kill_recovers_with_seeded_state(self, tmp_path):
        """Kill a join worker AFTER its state holds rows; the respawned
        worker must be re-seeded from the coordinator shadow so joins
        against pre-crash rows still match."""
        from risingwave_tpu.runtime.remote_fragments import RemoteWorkerDied
        d = str(tmp_path / "data")
        db = _join_db(d)
        db.run("INSERT INTO a VALUES (1, 10), (2, 20)")
        for _ in range(4):
            db.tick()
        rfs = find_remote(db, "rj")
        rfs.workers[0].proc.kill()
        with pytest.raises(RemoteWorkerDied):
            for _ in range(10):
                db.tick()
        rfs.shutdown()
        del db
        db2 = Database(data_dir=d)
        for _ in range(3):
            db2.tick()
        # the crashed-away left rows must still be joinable: they were
        # seeded into the fresh workers from the shadow tables
        db2.run("INSERT INTO b VALUES (1, 100), (2, 200)")
        for _ in range(4):
            db2.tick()
        assert sorted(db2.query("SELECT * FROM rj")) == \
            [(10, 100), (20, 200)]
        # and no double rows from seed replay
        db2.run("INSERT INTO a VALUES (1, 11)")
        for _ in range(4):
            db2.tick()
        assert sorted(db2.query("SELECT * FROM rj")) == \
            [(10, 100), (11, 100), (20, 200)]
        find_remote(db2, "rj").shutdown()


def test_heartbeat_detects_quiescent_worker_death():
    """A worker dying while the job is idle (no traffic in flight) must
    surface at the NEXT tick via the heartbeat sweep, not hang until
    traffic next touches the stream (meta heartbeat/expire analog)."""
    from risingwave_tpu.runtime.remote_fragments import RemoteWorkerDied
    db = Database()
    db.run(SRC.format(n=2048, c=32))       # drains almost immediately
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement = 'process'")
    db.run(MV)
    drive(db, 2048, 32)                    # source exhausted: quiescent
    rfs = find_remote(db, "q4")
    rfs.workers[1].proc.kill()
    rfs.workers[1].proc.wait()
    with pytest.raises(RemoteWorkerDied, match="heartbeat"):
        for _ in range(3):
            db.tick()
    rfs.shutdown()


class TestRemoteRetractableAgg:
    """Owned-group stateful aggs across worker processes: multiset
    min/max exact under retraction, shadow-seeded recovery (the VERDICT
    r04 item: retractable aggs ship their state across worker death)."""

    AGG_MV = ("CREATE MATERIALIZED VIEW ra AS SELECT k, count(*) AS c,"
              " min(v) AS lo, max(v) AS hi FROM t GROUP BY k")

    def _mk(self, d=None):
        db = Database(data_dir=d) if d else Database()
        db.run("CREATE TABLE t (k BIGINT, v BIGINT)")
        db.run("SET streaming_parallelism = 2")
        db.run("SET streaming_placement = 'process'")
        db.run(self.AGG_MV)
        return db

    def test_retraction_exactness(self):
        db = self._mk()
        rfs = find_remote(db, "ra")
        assert len(rfs.workers) == 2
        db.run("INSERT INTO t VALUES (1, 10), (1, 5), (2, 7), (2, 9)")
        for _ in range(4):
            db.tick()
        assert sorted(db.query("SELECT * FROM ra")) == \
            [(1, 2, 5, 10), (2, 2, 7, 9)]
        db.run("DELETE FROM t WHERE v = 5")
        for _ in range(4):
            db.tick()
        # the multiset state retracts the old min exactly
        assert sorted(db.query("SELECT * FROM ra")) == \
            [(1, 1, 10, 10), (2, 2, 7, 9)]
        rfs.shutdown()

    def test_worker_kill_reseeds_agg_state(self, tmp_path):
        from risingwave_tpu.runtime.remote_fragments import RemoteWorkerDied
        d = str(tmp_path / "data")
        db = self._mk(d)
        db.run("INSERT INTO t VALUES (1, 10), (1, 5), (2, 7)")
        for _ in range(4):
            db.tick()
        rfs = find_remote(db, "ra")
        rfs.workers[0].proc.kill()
        with pytest.raises(RemoteWorkerDied):
            for _ in range(10):
                db.tick()
        rfs.shutdown()
        del db
        db2 = Database(data_dir=d)
        for _ in range(3):
            db2.tick()
        assert sorted(db2.query("SELECT * FROM ra")) == \
            [(1, 2, 5, 10), (2, 1, 7, 7)]
        # retraction against RESEEDED worker state: min(5) must retract
        db2.run("DELETE FROM t WHERE v = 5")
        for _ in range(4):
            db2.tick()
        assert sorted(db2.query("SELECT * FROM ra")) == \
            [(1, 1, 10, 10), (2, 1, 7, 7)]
        find_remote(db2, "ra").shutdown()
