"""ISSUE 9 observability plane: streaming EXPLAIN ANALYZE, source->MV
freshness tracing, unified Perfetto export, and skew telemetry.

Acceptance contract under test: EXPLAIN ANALYZE on a running fused q5
returns a per-operator tree whose eps/occupancy columns agree with
rw_fused_node_stats; rw_mv_freshness reports end-to-end staleness
within one epoch cadence of ground truth on a datagen source (and stays
monotonic across a PR 8-style worker respawn); `risectl trace export`
output is valid Chrome trace-event JSON with monotonic per-track
timestamps; the clock-offset estimator recovers a known skew; and
rw_key_skew carries vnode-occupancy + heavy-hitter rows consistent with
the node-stats table."""
import json
import os
import threading
import time

import pytest

from risingwave_tpu.config import DeviceConfig, ROBUSTNESS
from risingwave_tpu.sql import Database

N = 5_000
CHUNK = 32

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           " nexmark.table='bid', nexmark.max.events='{n}',"
           " nexmark.chunk.size='{c}')")
Q4 = ("CREATE MATERIALIZED VIEW q4 AS SELECT auction, count(*) AS c,"
      " sum(price) AS s, max(price) AS m FROM bid GROUP BY auction")
Q5 = """CREATE MATERIALIZED VIEW q5 AS
SELECT AuctionBids.auction, AuctionBids.num FROM (
    SELECT bid.auction, count(*) AS num, window_start AS starttime
    FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
    GROUP BY window_start, bid.auction
) AS AuctionBids
JOIN (
    SELECT max(CountBids.num) AS maxn, CountBids.starttime_c
    FROM (
        SELECT count(*) AS num, window_start AS starttime_c
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY bid.auction, window_start
    ) AS CountBids
    GROUP BY CountBids.starttime_c
) AS MaxBids
ON AuctionBids.starttime = MaxBids.starttime_c
   AND AuctionBids.num >= MaxBids.maxn"""


def drive(db, n=N, chunk=CHUNK):
    for _ in range(n // (64 * chunk) + 3):
        db.tick()


def _fused_db(mv_sql=Q4, data_dir=None, n=N, chunk=CHUNK):
    db = Database(device=DeviceConfig(capacity=512, aot_compile=False),
                  data_dir=data_dir)
    db.run(BID_SRC.format(n=n, c=chunk))
    db.run(mv_sql)
    name = mv_sql.split()[3]
    assert (db.catalog.get(name).runtime or {}).get("fused_job") \
        is not None
    drive(db, n, chunk)
    return db


# ---------------------------------------------------------------------------
# clock-offset estimator
# ---------------------------------------------------------------------------


def test_clock_offset_recovers_known_skew():
    from risingwave_tpu.utils.export import estimate_clock_offset
    # worker clock runs 3.5s BEHIND the coordinator; one-way delays vary
    off = 3.5
    delays = [0.080, 0.004, 0.200, 0.0, 0.035]
    samples = [(1000.0 + i, 1000.0 + i + off + d)
               for i, d in enumerate(delays)]
    est = estimate_clock_offset(samples)
    assert abs(est - off) < 1e-9          # one sample had zero delay


def test_clock_offset_negative_skew_and_bounds():
    from risingwave_tpu.utils.export import estimate_clock_offset
    # worker clock AHEAD of the coordinator: offset is negative, and the
    # estimate is exact up to the smallest delay in the sample set
    off = -12.25
    delays = [0.050, 0.010, 0.030]
    samples = [(5000.0 + i, 5000.0 + i + off + d)
               for i, d in enumerate(delays)]
    est = estimate_clock_offset(samples)
    assert off <= est <= off + min(delays) + 1e-9


def test_clock_offset_empty():
    from risingwave_tpu.utils.export import estimate_clock_offset
    assert estimate_clock_offset([]) is None


# ---------------------------------------------------------------------------
# streaming EXPLAIN ANALYZE
# ---------------------------------------------------------------------------


def test_explain_analyze_parses():
    from risingwave_tpu.sql import ast as A
    from risingwave_tpu.sql.parser import parse_sql
    (stmt,) = parse_sql("EXPLAIN ANALYZE my_mv")
    assert isinstance(stmt, A.ExplainAnalyze) and stmt.target == "my_mv"
    (stmt,) = parse_sql("EXPLAIN SELECT 1")     # plain EXPLAIN unchanged
    assert isinstance(stmt, A.Explain)


def test_explain_analyze_q5_agrees_with_node_stats(monkeypatch):
    """The acceptance snapshot: per-operator tree of a RUNNING fused q5
    whose rows/eps/occupancy agree with rw_fused_node_stats."""
    monkeypatch.setenv("RW_SKEW_STATS", "1")   # conftest pins it off
    db = _fused_db(Q5, n=2048)
    out = db.run("EXPLAIN ANALYZE q5")[0]
    assert isinstance(out, str)
    lines = out.splitlines()
    assert lines[0].startswith("Streaming EXPLAIN ANALYZE: q5 (fused")
    assert lines[1].startswith("phase share:") and "dispatch" in lines[1]
    # the q5 shape is visible: hop, two agg chains, a join, the pair MV
    assert any("JoinNode" in ln for ln in lines)
    assert any("HopNode" in ln for ln in lines)
    assert sum("AggNode" in ln for ln in lines) >= 2
    by_node = {}
    for ln in lines[2:]:
        body = ln.strip().lstrip("-> ")
        idx = int(body.split(":", 1)[0])
        by_node[idx] = body
    rows = db.query("SELECT * FROM rw_fused_node_stats")
    assert rows
    for (_job, node, _t, slot, rows_in, rows_out, entries, cap, _occ,
         _hbm, _ov) in rows:
        body = by_node[node]
        assert f"rows_in={rows_in}" in body
        assert f"rows_out={rows_out}" in body
        if slot != "-":
            assert f"{slot}={entries}/{cap}" in body
    # eps columns derive from the same row counters (rows / elapsed)
    job = db._fused["q5"]
    elapsed = time.monotonic() - job.t_created
    for (_job, node, _t, slot, rows_in, _ro, _e, _c, _o, _h,
         _ov) in rows:
        import re
        m = re.search(r"eps_in=(\d+)", by_node[node])
        assert m is not None
        # rendered earlier than `elapsed` was sampled, so rendered eps
        # can only be >= the recomputed bound
        assert int(m.group(1)) >= int(rows_in / elapsed) - 1
    # skew telemetry rides the same tree
    assert any("skew=" in ln for ln in lines)


def test_explain_analyze_host_tree_and_rejections():
    db = Database()      # no device: host executor path
    db.run("CREATE TABLE t (v BIGINT)")
    db.run("CREATE MATERIALIZED VIEW m AS SELECT count(*) AS n FROM t")
    db.run("INSERT INTO t VALUES (1), (2)")
    out = db.run("EXPLAIN ANALYZE m")[0]
    assert out.startswith("Streaming EXPLAIN ANALYZE: m (host")
    assert "Materialize" in out or "Agg" in out
    with pytest.raises(KeyError):
        db.run("EXPLAIN ANALYZE nope")


# ---------------------------------------------------------------------------
# source->MV freshness
# ---------------------------------------------------------------------------


def test_datagen_freshness_within_tick_of_ground_truth():
    """Ground truth on a datagen source: a chunk is minted, materialized
    and committed inside ONE tick (checkpoint_frequency=1), so recorded
    freshness must stay within a tick's wall (one epoch cadence)."""
    db = Database(checkpoint_frequency=1)
    db.run("CREATE SOURCE s (v BIGINT) WITH (connector='datagen',"
           " rows.per.poll='256', datagen.max.rows='2048')")
    db.run("CREATE MATERIALIZED VIEW msum AS SELECT count(*) AS n,"
           " sum(v) AS s FROM s")
    max_tick = 0.0
    for _ in range(12):
        t0 = time.perf_counter()
        db.tick()
        max_tick = max(max_tick, time.perf_counter() - t0)
    hist = db._freshness.history("msum")
    assert hist, "commits must record freshness"
    flowing = [h for h in hist if h[3] > 0]
    assert all(h[3] >= 0 for h in hist)
    # ground truth bound: ingest->commit happens inside one tick; allow
    # 2 ticks + slack for loaded hosts
    assert min(h[3] for h in hist) <= 2 * max_tick + 0.25, flowing
    rows = db.query("SELECT * FROM rw_mv_freshness WHERE mv = 'msum'")
    assert len(rows) == 1
    (_mv, _e, ingest, commit, fresh, stale, p50, p99, commits) = rows[0]
    # the SELECT itself ticks a barrier first, so it may add one commit
    assert commit >= ingest and fresh >= 0
    assert commits == len(db._freshness.history("msum"))
    assert p50 <= p99
    # staleness recomputes at SELECT time: it GROWS while nothing commits
    time.sleep(0.05)
    stale2 = db.query(
        "SELECT * FROM rw_mv_freshness WHERE mv = 'msum'")[0][5]
    assert stale2 > stale


def test_freshness_anchors_on_checkpoint_window_oldest():
    """checkpoint_frequency > 1: the commit durably lands EVERY barrier
    since the last checkpoint, so freshness must anchor on the window's
    OLDEST ingest stamp — the sealing barrier's own stamp would report
    the MV up to a whole window fresher than ground truth."""
    db = Database(checkpoint_frequency=3)
    db.run("CREATE SOURCE s (v BIGINT) WITH (connector='datagen',"
           " rows.per.poll='32')")
    db.run("CREATE MATERIALIZED VIEW m2 AS SELECT count(*) AS n FROM s")
    db.tick()                              # INITIAL (checkpoint) barrier
    sleep = 0.06
    n0 = len(db._freshness.history("m2"))
    while len(db._freshness.history("m2")) == n0:
        time.sleep(sleep)
        db.tick()
    last = db._freshness.history("m2")[-1]
    # the window spans >= 2 inter-tick sleeps; anchoring on the sealing
    # barrier would report <= ~1 sleep
    assert last[3] >= 1.6 * sleep, last


def test_fused_freshness_rows():
    db = _fused_db(Q4)
    rows = db.query("SELECT * FROM rw_mv_freshness WHERE mv = 'q4'")
    assert len(rows) == 1
    (_mv, _e, ingest, commit, fresh, _stale, p50, p99, commits) = rows[0]
    assert commits > 0 and commit >= ingest and 0 <= p50 <= p99
    # the histogram rode along (bench reads p50/p99 from it)
    from risingwave_tpu.utils.metrics import REGISTRY
    assert 'mv_freshness_seconds_count{mv="q4"}' in REGISTRY.expose()


@pytest.mark.chaos
def test_freshness_monotonic_across_respawn():
    """PR 8-style in-place respawn must not bend the freshness timeline:
    commit timestamps and epochs stay nondecreasing, freshness stays
    non-negative, and the worker's death is invisible in the series
    shape (only, possibly, in magnitude)."""
    saved = (ROBUSTNESS.respawn_backoff_s, ROBUSTNESS.spawn_backoff_s)
    ROBUSTNESS.respawn_backoff_s = ROBUSTNESS.spawn_backoff_s = 0.001
    try:
        db = Database(checkpoint_frequency=1)
        db.run(BID_SRC.format(n=30_000, c=256))
        db.run("SET streaming_parallelism = 2")
        db.run("SET streaming_placement = 'process'")
        db.run("SET streaming_supervision TO true")
        db.run(Q4)
        from risingwave_tpu.sql.database import _walk_executors
        r = None
        for e in _walk_executors(db.catalog.get("q4").runtime["shared"]
                                 .upstream):
            if getattr(e, "_remote", None) is not None:
                r = e._remote
        assert r is not None
        for _ in range(4):
            db.tick()
        r.workers[0].proc.kill()          # PR 8-style single-worker death
        for _ in range(10):
            db.tick()
        assert r.supervisor is not None and r.supervisor.respawns >= 1
        hist = db._freshness.history("q4")
        assert len(hist) >= 5
        for a, b in zip(hist, hist[1:]):
            assert b[2] >= a[2], "commit_ts must be nondecreasing"
            assert b[0] >= a[0], "epochs must be nondecreasing"
        assert all(h[3] >= 0 for h in hist)
        # the barrier decomposition recorded per-worker align sub-spans
        trace_rows = db.query("SELECT * FROM rw_barrier_trace")
        aligns = [t for t in trace_rows if t[2].startswith("worker:")]
        assert aligns and all(t[3] == "align" for t in aligns)
        r.shutdown()
    finally:
        (ROBUSTNESS.respawn_backoff_s, ROBUSTNESS.spawn_backoff_s) = saved


# ---------------------------------------------------------------------------
# unified Perfetto export
# ---------------------------------------------------------------------------


def test_chrome_export_valid_and_monotonic(tmp_path):
    from risingwave_tpu.utils.export import export_chrome, validate_chrome
    d = str(tmp_path / "d")
    db = _fused_db(Q4, data_dir=d)
    del db
    doc = export_chrome(d)
    assert validate_chrome(doc) == []
    evs = doc["traceEvents"]
    assert evs, "a fused run must export events"
    # survives a JSON round trip (the file Perfetto actually loads)
    doc2 = json.loads(json.dumps(doc))
    assert len(doc2["traceEvents"]) == len(evs)
    tracks = {(e["pid"], e["tid"]) for e in evs}
    assert ("coordinator", "barrier") in tracks
    assert ("fused:q4", "epoch") in tracks
    assert ("fused:q4", "phases") in tracks
    # every complete event is well-formed
    for e in evs:
        assert e["ph"] in ("X", "i")
        if e["ph"] == "X":
            assert e["dur"] >= 0


def test_ctl_trace_export_cli(tmp_path, capsys):
    from risingwave_tpu import ctl
    d = str(tmp_path / "d")
    _fused_db(Q4, data_dir=d)
    out = str(tmp_path / "trace.json")
    rc = ctl.main(["trace", "export", "--data-dir", d, "-o", out])
    assert rc == 0
    with open(out) as f:
        doc = json.load(f)
    assert doc["traceEvents"] and doc["displayTimeUnit"] == "ms"
    assert "perfetto" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# skew telemetry
# ---------------------------------------------------------------------------


def test_rw_key_skew_consistent_with_node_stats(monkeypatch):
    monkeypatch.setenv("RW_SKEW_STATS", "1")   # conftest pins it off
    db = _fused_db(Q4)
    skew = db.query("SELECT * FROM rw_key_skew WHERE job = 'q4'")
    assert skew
    occ = [r for r in skew if r[3] == "vnode_occ"]
    hot = [r for r in skew if r[3] == "hot_key"]
    ratio = [r for r in skew if r[3] == "skew_ratio"]
    assert len(occ) == 16 and len(ratio) == 1
    # groups only ever grow in q4, so the high-water occupancy histogram
    # sums exactly to the agg's live-entry count in rw_fused_node_stats
    agg_entries = [r[6] for r in
                   db.query("SELECT * FROM rw_fused_node_stats")
                   if r[2] == "AggNode" and r[3] == "main"]
    assert sum(r[6] for r in occ) == agg_entries[0]
    assert abs(sum(r[7] for r in occ) - 1.0) < 1e-9   # shares sum to 1
    assert ratio[0][7] >= 1.0
    # nexmark's hot-auction distribution produces real heavy hitters
    assert hot and all(r[6] > 0 for r in hot)
    counts = [r[6] for r in hot]
    assert counts == sorted(counts, reverse=True)


def test_skew_stats_off_removes_slots_and_changes_nothing_else(
        monkeypatch):
    # the CONFIG off-switch (no env override in play)
    monkeypatch.delenv("RW_SKEW_STATS", raising=False)
    db = Database(device=DeviceConfig(capacity=512, aot_compile=False,
                                      skew_stats=False))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    drive(db)
    assert db.query("SELECT * FROM rw_key_skew") == []
    job = db._fused["q4"]
    assert all(not n.skew for n in job.program.nodes)
    assert not any(s.startswith("skv") for _i, s in
                   job.program.stat_layout)


def test_skew_stats_env_kill_switch(monkeypatch):
    # RW_SKEW_STATS=0 force-disables even when the config says on —
    # the no-code-change operational kill switch
    monkeypatch.setenv("RW_SKEW_STATS", "0")
    db = Database(device=DeviceConfig(capacity=512, aot_compile=False,
                                      skew_stats=True))
    db.run(BID_SRC.format(n=N, c=CHUNK))
    db.run(Q4)
    assert all(not n.skew for n in db._fused["q4"].program.nodes)


# ---------------------------------------------------------------------------
# satellites: follow-tail, liveness recompute, remote label lint
# ---------------------------------------------------------------------------


def test_tail_jsonl_survives_rotation(tmp_path):
    from risingwave_tpu.utils.profile import tail_jsonl
    from risingwave_tpu.utils.trace import rotate_tail
    path = str(tmp_path / "t.jsonl")
    with open(path, "w") as f:
        for i in range(100):
            f.write(json.dumps({"i": i}) + "\n")
    got = []
    stop = threading.Event()

    def consume():
        for rec in tail_jsonl(path, poll_s=0.02, stop=stop,
                              from_start=True):
            got.append(rec)

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    deadline = time.time() + 5
    while len(got) < 100 and time.time() < deadline:
        time.sleep(0.01)
    assert len(got) == 100
    rotate_tail(path)                     # replaces the file (new inode)
    with open(path, "a") as f:
        f.write(json.dumps({"i": "post"}) + "\n")
    while not any(r.get("i") == "post" for r in got) \
            and time.time() < deadline:
        time.sleep(0.01)
    stop.set()
    t.join(timeout=5)
    assert any(r.get("i") == "post" for r in got), \
        "tail must survive rotate_tail and keep yielding"
    assert all(isinstance(r, dict) for r in got)    # no torn lines
    # the rotation's replacement file is the old tail the follower
    # already yielded — it must be deduped, not re-emitted
    seen = [r["i"] for r in got]
    assert len(seen) == len(set(seen)), "rotation replayed seen records"


def test_profiler_flush_is_single_writer(tmp_path):
    """Concurrent flushes (epoch loop + supervisor respawn) must never
    tear lines: hammer flush from two threads while a third appends
    events, then parse every line."""
    from risingwave_tpu.utils.profile import JobProfiler
    prof = JobProfiler("j", enabled=True)
    prof.attach(str(tmp_path))
    stop = threading.Event()

    def emit():
        i = 0
        while not stop.is_set():
            prof.compile_event("0:AggNode:%08x" % i, 0.001)
            i += 1

    def flusher():
        while not stop.is_set():
            prof.flush()

    threads = [threading.Thread(target=emit, daemon=True)] \
        + [threading.Thread(target=flusher, daemon=True)
           for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(timeout=5)
    prof.flush()
    with open(prof.path) as f:
        for line in f:
            json.loads(line)              # every line parses whole


def test_backpressured_worker_not_wedged():
    """An idle coordinator (undrained result channel) must not read —
    or reap — a healthy worker as wedged: ages recompute at SELECT time
    and queued output proves liveness."""
    from risingwave_tpu.runtime.remote_fragments import _RemoteSetBase

    class _P:
        pid = 1

        def poll(self):
            return None

    class _W:
        proc = _P()
        last_epoch = 7

    class _Ch:
        def __init__(self, buf):
            self.buf = buf
            self.capacity = 256

    s = _RemoteSetBase.__new__(_RemoteSetBase)
    s.kind = "partial"
    s.workers = [_W()]
    s.heartbeats = [time.time() - 10 * ROBUSTNESS.heartbeat_timeout_s]
    s._reaping = [False]
    s.channels = [_Ch(buf=["queued-chunk"])]
    assert s.liveness_rows("j")[0][5] == "ok"        # queued output
    s.channels = [_Ch(buf=[])]
    assert s.liveness_rows("j")[0][5] == "wedged?"   # genuinely stale
    s.heartbeats = [time.time()]
    assert s.liveness_rows("j")[0][5] == "ok"        # recomputed NOW


def test_lint_flags_remote_label_divergence():
    from risingwave_tpu.utils.metrics import MetricsRegistry, lint_registry
    reg = MetricsRegistry()
    fam = {"type": "histogram", "help": "h", "labels": ["fragment"],
           "samples": [[["agg"], {"counts": [1], "total": 1, "sum": 0.1,
                                  "buckets": [1.0]}]]}
    reg.merge_remote({"worker_lat": dict(fam)}, worker="w0")
    assert lint_registry(reg) == []
    fam2 = dict(fam)
    fam2["labels"] = ["fragment", "shard"]    # diverged label set
    reg.merge_remote({"worker_lat": fam2}, worker="w1")
    problems = lint_registry(reg)
    assert any("diverge" in p and "worker_lat" in p for p in problems)
