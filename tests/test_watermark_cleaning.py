"""Watermarks through joins + watermark-driven state cleaning
(VERDICT #6: EOWC works downstream of a join; windowed state stops
growing)."""
import numpy as np
import pytest

from risingwave_tpu.sql import Database

TS0 = 1_600_000_000_000_000  # usecs


def _ts(sec: int) -> str:
    import datetime
    dt = datetime.datetime.fromtimestamp(TS0 // 1_000_000 + sec,
                                         datetime.timezone.utc)
    return dt.strftime("'%Y-%m-%d %H:%M:%S'")


def _mk_joined(device):
    db = Database(device=device)
    db.run("CREATE TABLE a (ts TIMESTAMP, k INT, "
           "WATERMARK FOR ts AS ts - INTERVAL '0 seconds') "
           "WITH (connector='dml')")
    db.run("CREATE TABLE b (ts TIMESTAMP, v BIGINT, "
           "WATERMARK FOR ts AS ts - INTERVAL '0 seconds') "
           "WITH (connector='dml')")
    db.run("CREATE MATERIALIZED VIEW j AS SELECT a.ts, a.k, b.v "
           "FROM a JOIN b ON a.ts = b.ts")
    return db


@pytest.mark.parametrize("device", ["off", "on", 8])
def test_watermark_propagates_through_join(device):
    """The join must emit the min-aligned key watermark — a downstream
    EOWC-style consumer of the join output sees time advance."""
    from risingwave_tpu.ops.message import Watermark
    db = _mk_joined(device)
    mat = db.catalog.get("j").runtime["shared"].upstream

    seen = []
    orig = mat.on_watermark

    def spy(wm):
        seen.append((wm.col_idx, wm.value))
        return orig(wm)

    mat.on_watermark = spy
    db.run(f"INSERT INTO a VALUES ({_ts(10)}, 1)")
    db.run(f"INSERT INTO b VALUES ({_ts(5)}, 100)")
    db.run(f"INSERT INTO a VALUES ({_ts(20)}, 2)")
    db.run(f"INSERT INTO b VALUES ({_ts(30)}, 200)")
    db.run("FLUSH")
    assert seen, "join swallowed all watermarks"
    # aligned watermark = min(left_wm, right_wm); both output positions
    cols = {c for c, _ in seen}
    assert 0 in cols, "left key column watermark missing"
    vals = [v for _, v in seen]
    assert vals == sorted(vals), "watermark must be monotone"
    assert max(vals) <= TS0 + 20 * 1_000_000


@pytest.mark.parametrize("device", ["off", "on", 8])
def test_join_state_cleaned_below_watermark(device):
    """Rows below the aligned key watermark can never match again — both
    sides' state must shrink (soak: bounded, not monotonic)."""
    db = _mk_joined(device)
    mat = db.catalog.get("j").runtime["shared"].upstream

    def find_join(e):
        stack = [e]
        while stack:
            x = stack.pop()
            if type(x).__name__ in ("HashJoinExecutor",
                                    "DeviceHashJoinExecutor"):
                return x
            for attr in ("input", "port", "left", "right"):
                if getattr(x, attr, None) is not None:
                    stack.append(getattr(x, attr))
        raise AssertionError("join not found")

    join = find_join(mat.input if hasattr(mat, "input") else mat)
    sizes = []
    for t in range(0, 40, 2):
        db.run(f"INSERT INTO a VALUES ({_ts(t)}, {t})")
        db.run(f"INSERT INTO b VALUES ({_ts(t)}, {t * 10})")
        db.run("FLUSH")
        if hasattr(join, "sides"):         # host path
            n = sum(len(d) for s in join.sides.values()
                    for d in s.table.values())
        else:                              # device path
            n = sum(len(join.dicts[s].rows) for s in ("a", "b"))
        sizes.append(n)
    # with cleaning the state stays bounded by a small constant; without
    # it, 20 inserts/side would make 40 stored rows
    assert sizes[-1] <= 6, sizes
    assert max(sizes) < 12, sizes
    # and results are still exact
    oracle = sorted(db.query(
        "SELECT a.ts, a.k, b.v FROM a JOIN b ON a.ts = b.ts"))
    assert sorted(db.query("SELECT * FROM j")) == oracle
    assert len(oracle) == 20


@pytest.mark.parametrize("device", ["off", "on", 8])
def test_windowed_agg_state_cleaned(device):
    """Non-EOWC TUMBLE aggregation: group state for closed windows is
    dropped at barriers (the MV keeps its rows)."""
    db = Database(device=device)
    db.run("CREATE TABLE t (ts TIMESTAMP, v BIGINT, "
           "WATERMARK FOR ts AS ts - INTERVAL '0 seconds') "
           "WITH (connector='dml')")
    db.run("CREATE MATERIALIZED VIEW w AS SELECT window_start, count(*) AS c,"
           " max(v) AS m FROM TUMBLE(t, ts, INTERVAL '2 seconds') "
           "GROUP BY window_start")
    mat = db.catalog.get("w").runtime["shared"].upstream

    def find_agg(e):
        stack = [e]
        while stack:
            x = stack.pop()
            if type(x).__name__ in ("HashAggExecutor",
                                    "DeviceHashAggExecutor"):
                return x
            for attr in ("input", "port"):
                if getattr(x, attr, None) is not None:
                    stack.append(getattr(x, attr))
        raise AssertionError("agg not found")

    agg = find_agg(mat)
    sizes = []
    for t in range(0, 60, 2):
        db.run(f"INSERT INTO t VALUES ({_ts(t)}, {t})")
        db.run("FLUSH")
        if hasattr(agg, "groups"):
            sizes.append(len(agg.groups))
        else:
            sizes.append(len(agg.engine.live_main()[0]))
    assert sizes[-1] <= 4, sizes        # only open windows retain state
    assert max(sizes) <= 6, sizes
    # MV keeps every closed window's row
    rows = db.query("SELECT * FROM w")
    assert len(rows) >= 25
    assert sum(c for _, c, _ in rows) == 30


@pytest.mark.parametrize("device", ["off", "on"])
def test_eowc_downstream_of_join(device):
    """EMIT ON WINDOW CLOSE over a join: without watermark alignment in the
    join this stalls forever (round-1 VERDICT weak point #6)."""
    db = _mk_joined(device)
    db.run("CREATE MATERIALIZED VIEW e AS SELECT window_start, count(*) AS c"
           " FROM TUMBLE(j, ts, INTERVAL '4 seconds') GROUP BY window_start"
           " EMIT ON WINDOW CLOSE")
    for t in range(0, 20, 2):
        db.run(f"INSERT INTO a VALUES ({_ts(t)}, {t})")
        db.run(f"INSERT INTO b VALUES ({_ts(t)}, {t})")
        db.run("FLUSH")
    rows = sorted(db.query("SELECT * FROM e"))
    # windows fully below the aligned watermark (18s) have closed: at least
    # [0,4), [4,8), [8,12), [12,16) with 2 joined rows each
    assert len(rows) >= 4, rows
    assert all(c == 2 for _, c in rows), rows
