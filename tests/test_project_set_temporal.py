"""ProjectSet / table-function scan / temporal join.

Reference semantics: `src/stream/src/executor/project/project_set.rs`
(PG-style zip with NULL padding, projected_row_id identity),
`src/expr/impl/src/table_function/generate_series.rs` (inclusive bounds,
zero step errors), `src/stream/src/executor/temporal_join.rs:44`
(version-table lookups, append-only output, no retraction on version
change).
"""
import pytest

from risingwave_tpu.sql import Database


def nsort(rows):
    return sorted(rows, key=lambda r: tuple((v is None, v) for v in r))


def ticks(db, n=3):
    for _ in range(n):
        db.tick()


# ---------------------------------------------------------------------------
# FROM-clause table functions
# ---------------------------------------------------------------------------


def test_generate_series_batch():
    db = Database()
    assert db.query("SELECT * FROM generate_series(1, 5)") == \
        [(i,) for i in range(1, 6)]
    assert db.query("SELECT * FROM generate_series(1, 10, 3)") == \
        [(1,), (4,), (7,), (10,)]
    assert db.query("SELECT * FROM generate_series(5, 1, -2)") == \
        [(5,), (3,), (1,)]
    # empty series
    assert db.query("SELECT * FROM generate_series(5, 1)") == []


def test_generate_series_zero_step_errors():
    db = Database()
    with pytest.raises(Exception, match="step"):
        db.query("SELECT * FROM generate_series(1, 5, 0)")


def test_generate_series_timestamps():
    db = Database()
    rows = db.query(
        "SELECT * FROM generate_series("
        "CAST('2024-01-01 00:00:00' AS TIMESTAMP),"
        "CAST('2024-01-01 02:00:00' AS TIMESTAMP),"
        "INTERVAL '1' HOUR)")
    assert len(rows) == 3


def test_unnest_batch_and_mv():
    db = Database()
    assert db.query("SELECT * FROM unnest(ARRAY[3, 1, 2])") == \
        [(3,), (1,), (2,)]
    db.run("CREATE MATERIALIZED VIEW u AS"
           " SELECT * FROM unnest(ARRAY[7, 7, 8])")
    ticks(db)
    # duplicates preserved: _row_id keeps multiset identity
    assert nsort(db.query("SELECT * FROM u")) == [(7,), (7,), (8,)]


def test_mv_over_generate_series():
    db = Database()
    db.run("CREATE MATERIALIZED VIEW gs AS"
           " SELECT * FROM generate_series(2, 6, 2)")
    ticks(db)
    assert nsort(db.query("SELECT * FROM gs")) == [(2,), (4,), (6,)]


# ---------------------------------------------------------------------------
# ProjectSet (SRF in the SELECT list)
# ---------------------------------------------------------------------------


def test_project_set_expands_and_retracts():
    db = Database()
    db.run("CREATE TABLE t (a BIGINT, b BIGINT)")
    db.run("INSERT INTO t VALUES (1, 3), (10, 11)")
    db.tick()
    db.run("CREATE MATERIALIZED VIEW ps AS"
           " SELECT a, generate_series(a, b) AS g FROM t")
    ticks(db)
    assert nsort(db.query("SELECT * FROM ps")) == \
        [(1, 1), (1, 2), (1, 3), (10, 10), (10, 11)]
    # deletes retract exactly the expanded rows (deterministic expansion)
    db.run("DELETE FROM t WHERE a = 1")
    db.run("INSERT INTO t VALUES (20, 20)")
    ticks(db)
    assert nsort(db.query("SELECT * FROM ps")) == \
        [(10, 10), (10, 11), (20, 20)]


def test_project_set_zip_null_padding():
    """PG >= 10: multiple SRFs zip to the longest, shorter ones NULL-pad."""
    db = Database()
    db.run("CREATE TABLE t (a BIGINT, b BIGINT)")
    db.run("INSERT INTO t VALUES (10, 11)")
    db.tick()
    db.run("CREATE MATERIALIZED VIEW z AS SELECT a,"
           " generate_series(1, 2) AS g, unnest(ARRAY[a, b, 99]) AS u"
           " FROM t")
    ticks(db)
    assert nsort(db.query("SELECT * FROM z")) == \
        [(10, 1, 10), (10, 2, 11), (10, None, 99)]


def test_project_set_empty_expansion_drops_row():
    db = Database()
    db.run("CREATE TABLE t (a BIGINT, b BIGINT)")
    db.run("INSERT INTO t VALUES (5, 1), (1, 2)")   # (5,1): empty series
    db.tick()
    db.run("CREATE MATERIALIZED VIEW e AS"
           " SELECT generate_series(a, b) AS g FROM t")
    ticks(db)
    assert nsort(db.query("SELECT * FROM e")) == [(1,), (2,)]


# ---------------------------------------------------------------------------
# temporal join
# ---------------------------------------------------------------------------


def _dim_fact():
    db = Database()
    db.run("CREATE TABLE dim (k BIGINT PRIMARY KEY, name VARCHAR)")
    db.run("INSERT INTO dim VALUES (1, 'one'), (2, 'two')")
    db.tick()
    db.run("CREATE TABLE fact (k BIGINT, v BIGINT)")
    return db


def test_temporal_join_inner_lookup():
    db = _dim_fact()
    db.run("CREATE MATERIALIZED VIEW tj AS SELECT f.v, d.name FROM fact f"
           " JOIN dim FOR SYSTEM_TIME AS OF PROCTIME() AS d"
           " ON f.k = d.k")
    db.run("INSERT INTO fact VALUES (1, 100), (3, 300)")
    ticks(db)
    assert nsort(db.query("SELECT * FROM tj")) == [(100, "one")]


def test_temporal_join_no_retraction_on_version_change():
    """The defining temporal-join property: emitted rows are frozen; only
    NEW stream rows see the new version (`temporal_join.rs` semantics)."""
    db = _dim_fact()
    db.run("CREATE MATERIALIZED VIEW tj AS SELECT f.v, d.name FROM fact f"
           " JOIN dim FOR SYSTEM_TIME AS OF PROCTIME() AS d"
           " ON f.k = d.k")
    db.run("INSERT INTO fact VALUES (1, 100)")
    ticks(db)
    db.run("UPDATE dim SET name = 'uno' WHERE k = 1")
    db.tick()
    db.run("INSERT INTO fact VALUES (1, 101)")
    ticks(db)
    assert nsort(db.query("SELECT * FROM tj")) == \
        [(100, "one"), (101, "uno")]
    # a version DELETE doesn't retract either; new rows just stop matching
    db.run("DELETE FROM dim WHERE k = 1")
    db.tick()
    db.run("INSERT INTO fact VALUES (1, 102)")
    ticks(db)
    assert nsort(db.query("SELECT * FROM tj")) == \
        [(100, "one"), (101, "uno")]


def test_temporal_join_left_outer():
    db = _dim_fact()
    db.run("CREATE MATERIALIZED VIEW tj AS SELECT f.v, d.name FROM fact f"
           " LEFT JOIN dim FOR SYSTEM_TIME AS OF PROCTIME() AS d"
           " ON f.k = d.k")
    db.run("INSERT INTO fact VALUES (1, 100), (3, 300)")
    ticks(db)
    assert nsort(db.query("SELECT * FROM tj")) == \
        [(100, "one"), (300, None)]


def test_temporal_join_recovery(tmp_path):
    """The version index is state-backed: a restarted process rebuilds it
    and new stream rows look up the committed version."""
    d = str(tmp_path / "data")
    db = Database(data_dir=d)
    db.run("CREATE TABLE dim (k BIGINT PRIMARY KEY, name VARCHAR)")
    db.run("INSERT INTO dim VALUES (1, 'one')")
    db.tick()
    db.run("CREATE TABLE fact (k BIGINT, v BIGINT)")
    db.run("CREATE MATERIALIZED VIEW tj AS SELECT f.v, d.name FROM fact f"
           " JOIN dim FOR SYSTEM_TIME AS OF PROCTIME() AS d"
           " ON f.k = d.k")
    db.run("INSERT INTO fact VALUES (1, 100)")
    ticks(db)
    del db
    db2 = Database(data_dir=d)
    db2.run("INSERT INTO fact VALUES (1, 200)")
    ticks(db2)
    assert nsort(db2.query("SELECT * FROM tj")) == \
        [(100, "one"), (200, "one")]
