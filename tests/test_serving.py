"""Serving tier (ISSUE 19): epoch-versioned MV read cache, replica mesh
axis, chip-parallel SELECT serving.

The contract under test:

* `serving/read_cache.py` — one device pull per (MV, epoch) regardless
  of reader count (single-flight coalescing, asserted against
  `shard_exec.PULL_STATS`), staleness-bounded serving
  (`rw_serving_staleness_epochs`), cold start after restart/recovery.
* `FusedJob.mv_rows_versioned` — a pull torn by a racing commit retries
  until it brackets one consistent (epoch, rows).
* `SelectGate` per-session token accounting — one chatty session
  exhausts its own slice (SQLSTATE 53000) without starving others.
* Replica mesh axis — `DeviceConfig.replicas=2` lowers the SAME fused
  program onto a (shard, replica) 2-D mesh, state mirrored over the
  replica axis, and is BIT-IDENTICAL (row order included) to the 1-D
  replicas=1 mesh on q1/q3/q5-shaped plans; reads round-robin over
  replica columns.

The conftest forces 8 virtual CPU devices, so the 2-D runs use
shards=4 x replicas=2.
"""
import threading
import time

import pytest

from risingwave_tpu.config import ROBUSTNESS, DeviceConfig
from risingwave_tpu.device import shard_exec
from risingwave_tpu.serving import MVReadCache
from risingwave_tpu.sql import Database
from risingwave_tpu.utils import failpoint as fp
from risingwave_tpu.utils.overload import AdmissionRejected, SelectGate

# one event bound for every run in this file: the traced programs embed
# max_events, so a single N means each mesh config compiles its program
# set ONCE for the whole module (tier-1 budget)
N = 8192
CHUNK = 32          # fused epoch = 64 * CHUNK = 2048 events
TICKS = N // 2048 + 3

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           " nexmark.table='bid', nexmark.max.events='{n}',"
           " nexmark.chunk.size='{c}')")
AUCTION_SRC = ("CREATE SOURCE auction (id BIGINT, item_name VARCHAR,"
               " description VARCHAR, initial_bid BIGINT, reserve BIGINT,"
               " date_time TIMESTAMP, expires TIMESTAMP, seller BIGINT,"
               " category BIGINT, extra VARCHAR) WITH (connector='nexmark',"
               " nexmark.table='auction', nexmark.max.events='{n}',"
               " nexmark.chunk.size='{c}')")

Q1_MV = ("CREATE MATERIALIZED VIEW q1a AS SELECT bidder,"
         " count(*) AS n, sum(price) AS dol, max(price) AS top"
         " FROM bid GROUP BY bidder")
Q3_MV = ("CREATE MATERIALIZED VIEW q3a AS SELECT b.auction, b.price,"
         " a.seller, a.category FROM bid b JOIN auction a"
         " ON b.auction = a.id WHERE b.price > 500")
Q5_MV = """CREATE MATERIALIZED VIEW q5 AS
SELECT AuctionBids.auction, AuctionBids.num FROM (
    SELECT bid.auction, count(*) AS num, window_start AS starttime
    FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
    GROUP BY window_start, bid.auction
) AS AuctionBids
JOIN (
    SELECT max(CountBids.num) AS maxn, CountBids.starttime_c
    FROM (
        SELECT count(*) AS num, window_start AS starttime_c
        FROM HOP(bid, date_time, INTERVAL '2' SECOND, INTERVAL '10' SECOND)
        GROUP BY bid.auction, window_start
    ) AS CountBids
    GROUP BY CountBids.starttime_c
) AS MaxBids
ON AuctionBids.starttime = MaxBids.starttime_c
   AND AuctionBids.num >= MaxBids.maxn"""

_KNOBS = ("select_concurrency", "select_per_session", "serving_cache",
          "serving_staleness_epochs")


@pytest.fixture(autouse=True)
def _restore_knobs():
    saved = {k: getattr(ROBUSTNESS, k) for k in _KNOBS}
    fp.reset()
    shard_exec.reset_pull_stats()
    yield
    fp.reset()
    for k, v in saved.items():
        setattr(ROBUSTNESS, k, v)


def _fused(mv_sql, name, shards=1, srcs=(BID_SRC,), n=N, ticks=None,
           replicas=1, capacity=512, data_dir=None, sync=True):
    db = Database(device=DeviceConfig(capacity=capacity,
                                      mesh_shards=shards,
                                      replicas=replicas),
                  data_dir=data_dir)
    for s in srcs:
        db.run(s.format(n=n, c=CHUNK))
    db.run(mv_sql)
    job = db.catalog.get(name).runtime["fused_job"]
    assert job is not None, f"{name} must fuse"
    for _ in range(ticks if ticks is not None else n // 2048 + 3):
        db.tick()
    if sync:
        job.sync()
    return db, job


# ---------------------------------------------------------------------------
# MVReadCache unit semantics (no device)
# ---------------------------------------------------------------------------


def test_cache_fill_hit_and_staleness_bound():
    c = MVReadCache()
    pulls = []

    def fill_at(e):
        def fill():
            pulls.append(e)
            return e, [("rows", e)]
        return fill

    # cold: miss -> fill at epoch 5
    assert c.get("mv", 5, 0, fill_at(5)) == (5, [("rows", 5)])
    # same committed epoch: pure hit, no new pull
    assert c.get("mv", 5, 0, fill_at(5)) == (5, [("rows", 5)])
    assert pulls == [5]
    # commit advances to 7: staleness 0 refills ...
    assert c.get("mv", 7, 0, fill_at(7)) == (7, [("rows", 7)])
    assert pulls == [5, 7]
    # ... staleness 2 would have served the epoch-5 snapshot at 7
    c2 = MVReadCache()
    c2.get("mv", 5, 0, fill_at(5))
    assert c2.get("mv", 7, 2, fill_at(7)) == (5, [("rows", 5)])
    # but not at 8 (5 < 8 - 2)
    assert c2.get("mv", 8, 2, fill_at(8)) == (8, [("rows", 8)])
    # peek never fills
    assert c2.peek("mv", 8) == [("rows", 8)]
    assert c2.peek("mv", 9) is None
    assert c2.peek("other", 0) is None
    # invalidate -> cold again
    c2.invalidate("mv")
    assert c2.peek("mv", 0) is None


def test_cache_single_flight_coalesces_concurrent_readers():
    c = MVReadCache()
    fills = []
    gate = threading.Event()

    def slow_fill():
        fills.append(1)
        gate.wait(5.0)          # hold all other readers on the cond
        return 3, [("v",)]

    results = []

    def reader():
        results.append(c.get("mv", 3, 0, slow_fill))

    threads = [threading.Thread(target=reader) for _ in range(16)]
    for t in threads:
        t.start()
    # let every reader reach the cache before the fill completes
    deadline = time.time() + 5.0
    while len(fills) < 1 and time.time() < deadline:
        time.sleep(0.01)
    time.sleep(0.2)
    gate.set()
    for t in threads:
        t.join(10.0)
    assert len(fills) == 1, "single-flight: exactly one fill"
    assert results == [(3, [("v",)])] * 16
    st = c.stats()
    assert st["fills"] == 1 and st["misses"] == 1
    assert st["hits"] == 15 and st["coalesced"] >= 1


# ---------------------------------------------------------------------------
# end-to-end: a SELECT storm costs one device pull per (MV, epoch)
# ---------------------------------------------------------------------------


def test_select_storm_one_device_pull_per_mv_epoch():
    """64 readers between two checkpoints -> exactly ONE device pull;
    the next committed epoch costs exactly one more (the acceptance
    invariant, counted at the `merge_keyed_pull` device_get)."""
    db, job = _fused(Q1_MV, "q1a", ticks=2)
    assert job.counter > 0
    db.read_cache.invalidate()
    shard_exec.reset_pull_stats()

    rows_out = []

    def storm():
        errs = []

        def reader():
            try:
                rows_out.append(db._serve_mv_rows("q1a", job))
            except Exception as e:          # pragma: no cover
                errs.append(e)
        ts = [threading.Thread(target=reader) for _ in range(64)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60.0)
        assert not errs

    storm()
    assert shard_exec.PULL_STATS["device_pulls"] == 1, \
        "64-reader storm must coalesce onto one device pull"
    assert len(rows_out) == 64
    assert all(r == rows_out[0] for r in rows_out)
    st = db.read_cache.stats()
    assert st["fills"] == 1 and st["hits"] + st["misses"] == 64

    # drive one more epoch; the counter moves, the old snapshot goes
    # unservable at staleness 0, and a second storm costs exactly one
    # more pull
    c0 = job.counter
    db.tick()
    job.sync()
    assert job.counter > c0
    shard_exec.reset_pull_stats()
    rows_out.clear()
    storm()
    assert shard_exec.PULL_STATS["device_pulls"] == 1
    assert all(r == rows_out[0] for r in rows_out)


def test_staleness_bound_serves_without_pull():
    db, job = _fused(Q1_MV, "q1a", ticks=2)
    # fill the cache at the current counter
    served = db._serve_mv_rows("q1a", job)
    c0 = job.counter
    db.tick()                   # next fused epoch dispatches
    job.sync()
    delta = int(job.counter) - int(c0)
    assert delta >= 1
    # bounded staleness covers the advance: host-memory hit, zero pulls
    # (the knob is in fused epochs; delta is in events)
    e = int(job.program.epoch_events)
    ROBUSTNESS.serving_staleness_epochs = -(-delta // e)
    shard_exec.reset_pull_stats()
    assert db._serve_mv_rows("q1a", job) == served
    assert shard_exec.PULL_STATS["device_pulls"] == 0
    # always-fresh refills with exactly one pull
    ROBUSTNESS.serving_staleness_epochs = 0
    shard_exec.reset_pull_stats()
    db._serve_mv_rows("q1a", job)
    assert shard_exec.PULL_STATS["device_pulls"] == 1
    assert db.read_cache.report()[0][1] == job.counter


def test_serving_cache_knob_off_bypasses_cache():
    ROBUSTNESS.serving_cache = False
    db, job = _fused(Q1_MV, "q1a")
    want = job.mv_rows_now()
    shard_exec.reset_pull_stats()
    assert db._serve_mv_rows("q1a", job) == want
    assert db._serve_mv_rows("q1a", job) == want
    # no cache: every read pulls
    assert shard_exec.PULL_STATS["device_pulls"] == 2
    assert db.read_cache.report() == []


def test_drop_mv_invalidates_cache_entry():
    db, job = _fused(Q1_MV, "q1a")
    db._serve_mv_rows("q1a", job)
    assert db.read_cache.report()[0][0] == "q1a"
    db.run("DROP MATERIALIZED VIEW q1a")
    assert db.read_cache.report() == []


# ---------------------------------------------------------------------------
# torn-read regression: commit lands mid-pull
# ---------------------------------------------------------------------------


def test_mv_rows_versioned_retries_torn_pull():
    """A commit injected mid-pull (the counter moves while rows are in
    flight) must NOT surface the torn snapshot: `mv_rows_versioned`
    retries until one pull is bracketed by a stable (counter,
    committed) pair."""
    db, job = _fused(Q1_MV, "q1a")
    want = job.mv_rows_now()
    orig = job.mv_rows_now
    calls = {"n": 0}

    def torn_once():
        calls["n"] += 1
        rows = orig()
        if calls["n"] == 1:
            # simulate the racing dispatch+commit landing mid-pull
            job.counter += 1
            job.committed += 1
            return [("torn", -1, -1, -1)]
        return rows

    job.mv_rows_now = torn_once
    try:
        epoch, rows = job.mv_rows_versioned()
    finally:
        job.mv_rows_now = orig
        job.counter -= 1
        job.committed -= 1
    assert calls["n"] == 2, "torn pull must retry exactly once here"
    assert rows == want, "the torn snapshot must never be returned"
    assert epoch == job.counter + 1


# ---------------------------------------------------------------------------
# per-session SELECT fairness (token accounting, SQLSTATE 53000)
# ---------------------------------------------------------------------------


def test_select_gate_per_session_slice():
    ROBUSTNESS.select_concurrency = 4
    ROBUSTNESS.select_per_session = 1
    g = SelectGate()
    assert g.enter(session="a") is True
    # the chatty session exhausts ITS slice ...
    with pytest.raises(AdmissionRejected) as ei:
        g.enter(session="a")
    assert ei.value.sqlstate == "53000"
    assert "RW_SELECT_PER_SESSION" in str(ei.value)
    # ... while another session still admits under the shared budget
    assert g.enter(session="b") is True
    assert g.rejected == 1
    g.leave(session="a")
    assert g.enter(session="a") is True     # slot returned
    g.leave(session="a")
    g.leave(session="b")
    assert g.active == 0 and g.session_active == {}


def test_select_gate_global_bound_and_knob_off():
    ROBUSTNESS.select_concurrency = 1
    ROBUSTNESS.select_per_session = 8
    g = SelectGate()
    assert g.enter(session="a") is True
    with pytest.raises(AdmissionRejected) as ei:
        g.enter(session="b")                # global budget, not a's slice
    assert "RW_SELECT_CONCURRENCY" in str(ei.value)
    g.leave(session="a")
    # per-session cap <= 0 disables only the per-session slice
    ROBUSTNESS.select_concurrency = 4
    ROBUSTNESS.select_per_session = 0
    for _ in range(3):
        assert g.enter(session="a") is True
    for _ in range(3):
        g.leave(session="a")
    # concurrency <= 0 disables the gate entirely (enter() -> False)
    ROBUSTNESS.select_concurrency = 0
    assert g.enter(session="a") is False
    assert g.enter() is False


# ---------------------------------------------------------------------------
# serving chaos: recovery, restart, policy switch
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("point", ["fused.dispatch", "fused.device_sync"])
def test_serving_cache_across_inplace_recovery(point):
    """A fused.* fault mid-run heals in place; cached serving after the
    recovery is bit-identical to an undisturbed run."""
    db0, job0 = _fused(Q1_MV, "q1a")
    want = db0._serve_mv_rows("q1a", job0)
    fp.arm(point, 1.0, 0, 1)
    try:
        db, job = _fused(Q1_MV, "q1a")
    finally:
        fp.reset()
    assert job.recoveries == 1, point
    assert db._serve_mv_rows("q1a", job) == want, point
    # and the cache now holds the healed snapshot
    assert db.read_cache.peek("q1a", int(job.counter)) == want


def test_coordinator_restart_cache_rebuilds_cold(tmp_path):
    """Restart: the cache is NOT persisted — a reopened coordinator
    starts cold and the first read repopulates from the device."""
    d = str(tmp_path / "data")
    db, job = _fused(Q1_MV, "q1a", data_dir=d)
    want = sorted(db._serve_mv_rows("q1a", job))
    assert db.read_cache.stats()["fills"] == 1
    del db

    db2 = Database(data_dir=d, device=DeviceConfig(capacity=512))
    assert db2.read_cache.report() == [], "restart must start cold"
    job2 = db2.catalog.get("q1a").runtime["fused_job"]
    shard_exec.reset_pull_stats()
    got = sorted(db2._serve_mv_rows("q1a", job2))
    assert got == want
    st = db2.read_cache.stats()
    assert st["fills"] == 1 and st["misses"] == 1
    assert shard_exec.PULL_STATS["device_pulls"] == 1
    # second read: host-memory hit, no new pull
    assert sorted(db2._serve_mv_rows("q1a", job2)) == want
    assert shard_exec.PULL_STATS["device_pulls"] == 1


SKEW_BID_SRC = BID_SRC.replace("nexmark.chunk.size='{c}')",
                               "nexmark.chunk.size='{c}',"
                               " nexmark.key.dist='zipf:4')")


@pytest.mark.mesh
@pytest.mark.chaos
@pytest.mark.slow
def test_serving_across_vnode_rebalance_policy_switch(monkeypatch):
    """A vnode-rebalance policy switch mid-stream (skewed keys, low
    threshold) must not wedge the serving path: post-adoption cached
    reads match a direct pull."""
    monkeypatch.setenv("RW_SKEW_STATS", "1")
    monkeypatch.setenv("RW_VNODE_REBALANCE", "1")
    db = Database(device=DeviceConfig(capacity=2048, mesh_shards=4,
                                      compile_buckets=0,
                                      rebalance_threshold=1.2))
    db.run(SKEW_BID_SRC.format(n=N, c=CHUNK))
    db.run(Q1_MV)
    job = db.catalog.get("q1a").runtime["fused_job"]
    assert job is not None
    for _ in range(TICKS):
        db.tick()
    job.sync()
    for _ in range(60):         # staged policy adopts at a checkpoint
        if job._pending_policy is None:
            break
        time.sleep(0.1)
        db.tick()
    db.tick()
    assert job.rebalances >= 1, "skew policy never adopted"
    want = job.mv_rows_now()
    db.read_cache.invalidate()  # recovery/rebalance convention: cold
    assert db._serve_mv_rows("q1a", job) == want
    assert db.read_cache.peek("q1a", int(job.counter)) == want


# ---------------------------------------------------------------------------
# replica mesh axis: 2-D (shard x replica) bit-identity vs 1-D
# ---------------------------------------------------------------------------


def _rows(mv_sql, name, shards, replicas, srcs=(BID_SRC,)):
    from risingwave_tpu.parallel.mesh import (REPLICA_AXIS, SHARD_AXIS,
                                              data_shards, mesh_replicas)
    db, job = _fused(mv_sql, name, shards=shards, srcs=srcs,
                     replicas=replicas)
    mesh = job.program.mesh
    assert mesh is not None
    assert data_shards(mesh) == shards
    if replicas > 1:
        assert mesh.axis_names == (SHARD_AXIS, REPLICA_AXIS)
        assert mesh_replicas(mesh) == replicas
        assert mesh.devices.size == shards * replicas
    else:
        # replicas=1 lowers to the EXACT old 1-D mesh
        assert mesh.axis_names == (SHARD_AXIS,)
        assert mesh.devices.size == shards
    rows = db.query(f"SELECT * FROM {name}")
    return rows, job


@pytest.mark.mesh
@pytest.mark.parametrize("mv_sql,name,srcs", [
    (Q1_MV, "q1a", (BID_SRC,)),
    # q3/q5 compile two extra fused program sets each — out of the
    # tier-1 budget, still covered by the slow/mesh lane
    pytest.param(Q3_MV, "q3a", (BID_SRC, AUCTION_SRC),
                 marks=pytest.mark.slow),
], ids=["q1", "q3"])
def test_replica_mesh_bit_identity(mv_sql, name, srcs):
    """The same fused program over a (4, 2) named mesh — state sharded
    over `shard`, mirrored over `replica` — is bit-identical (row order
    included) to the 1-D 4-shard mesh."""
    want, _ = _rows(mv_sql, name, 4, 1, srcs)
    got, _ = _rows(mv_sql, name, 4, 2, srcs)
    assert got == want


@pytest.mark.mesh
@pytest.mark.slow
def test_replica_mesh_bit_identity_q5():
    want, _ = _rows(Q5_MV, "q5", 4, 1)
    got, _ = _rows(Q5_MV, "q5", 4, 2)
    assert got == want


@pytest.mark.mesh
def test_replica_reads_round_robin_over_replica_columns():
    """With replicas=2 the gathered MV snapshot is addressable on every
    device; successive pulls alternate replica columns (chip-parallel
    read serving), tracked in PULL_STATS['replica_pulls']."""
    db, job = _fused(Q1_MV, "q1a", shards=4, replicas=2)
    shard_exec.reset_pull_stats()
    a = job.mv_rows_now()
    b = job.mv_rows_now()
    assert a == b
    reps = shard_exec.PULL_STATS["replica_pulls"]
    assert set(reps) == {0, 1}, f"round-robin over replicas, got {reps}"
    assert shard_exec.PULL_STATS["device_pulls"] == 2
