"""SpillStateStore durability: checkpoint, recovery, compaction, crash."""
import os

import pytest

from risingwave_tpu.core import Op, Schema, StreamChunk, dtypes as T
from risingwave_tpu.connectors import ListReader
from risingwave_tpu.expr import AggCall
from risingwave_tpu.ops import (BarrierInjector, BatchScan, ConflictBehavior,
                                HashAggExecutor, MaterializeExecutor,
                                SourceExecutor)
from risingwave_tpu.runtime import StreamJob
from risingwave_tpu.state import SpillStateStore, StateTable

S = Schema.of(("k", T.INT64), ("v", T.INT64))


def test_roundtrip_across_reopen(tmp_path):
    d = str(tmp_path)
    st = SpillStateStore(d)
    st.ingest_batch(1, [(b"a", (1, 2)), (b"b", (3, 4))], epoch=100)
    st.commit_epoch(100)
    st.ingest_batch(1, [(b"a", None), (b"c", (5, 6))], epoch=200)
    st.commit_epoch(200)

    st2 = SpillStateStore(d)
    assert st2.committed_epoch == 200
    assert st2.get(1, b"a") is None
    assert st2.get(1, b"b") == (3, 4)
    assert st2.get(1, b"c") == (5, 6)


def test_uncommitted_epoch_lost_on_reopen(tmp_path):
    d = str(tmp_path)
    st = SpillStateStore(d)
    st.ingest_batch(1, [(b"a", (1,))], epoch=100)
    st.commit_epoch(100)
    st.ingest_batch(1, [(b"b", (2,))], epoch=200)  # never committed
    st2 = SpillStateStore(d)
    assert st2.get(1, b"a") == (1,)
    assert st2.get(1, b"b") is None  # checkpoint semantics: gone


def test_compaction_keeps_data_and_prunes_files(tmp_path):
    d = str(tmp_path)
    st = SpillStateStore(d)
    for e in range(1, 12):
        st.ingest_batch(3, [(f"k{e}".encode(), (e,))], epoch=e * 10)
        st.commit_epoch(e * 10)
    runs = os.listdir(os.path.join(d, "runs"))
    assert len([r for r in runs if r.startswith("t3_")]) < 11  # compacted
    st2 = SpillStateStore(d)
    assert st2.table_len(3) == 11
    for e in range(1, 12):
        assert st2.get(3, f"k{e}".encode()) == (e,)


def test_agg_job_recovery_over_spill_store(tmp_path):
    """Kill a streaming agg job; a fresh process picks up from the committed
    epoch with identical MV contents (SURVEY §5 checkpoint/resume)."""
    d = str(tmp_path)

    def build_job(store, chunks):
        inj = BarrierInjector()
        src = SourceExecutor(S, ListReader(chunks), inj)
        agg_state = StateTable(store, 10, [T.INT64, T.BYTEA], [0])
        agg = HashAggExecutor(src, [0], [AggCall("count"),
                                         AggCall("sum", _v())],
                              state_table=agg_state)
        mv = StateTable(store, 11, agg.schema.dtypes, [0])
        mat = MaterializeExecutor(agg, mv, ConflictBehavior.OVERWRITE)
        return StreamJob(mat, inj, store), mv

    def _v():
        from risingwave_tpu.expr import InputRef
        return InputRef(1, T.INT64)

    c1 = StreamChunk.from_rows(S.dtypes, [(Op.INSERT, (1, 10)),
                                          (Op.INSERT, (2, 20))])
    c2 = StreamChunk.from_rows(S.dtypes, [(Op.INSERT, (1, 5))])

    store = SpillStateStore(d)
    job, _ = build_job(store, [c1])
    job.run_until_idle()
    del store, job  # "crash"

    store2 = SpillStateStore(d)
    job2, mv = build_job(store2, [c2])
    job2.run_until_idle()
    rows = sorted(BatchScan(mv, None).rows())
    assert rows == [(1, 2, 15), (2, 1, 20)]
