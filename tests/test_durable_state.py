"""SpillStateStore durability: checkpoint, recovery, compaction, crash."""
import os

import pytest

from risingwave_tpu.core import Op, Schema, StreamChunk, dtypes as T
from risingwave_tpu.connectors import ListReader
from risingwave_tpu.expr import AggCall
from risingwave_tpu.ops import (BarrierInjector, BatchScan, ConflictBehavior,
                                HashAggExecutor, MaterializeExecutor,
                                SourceExecutor)
from risingwave_tpu.runtime import StreamJob
from risingwave_tpu.state import SpillStateStore, StateTable

S = Schema.of(("k", T.INT64), ("v", T.INT64))


def test_roundtrip_across_reopen(tmp_path):
    d = str(tmp_path)
    st = SpillStateStore(d)
    st.ingest_batch(1, [(b"a", (1, 2)), (b"b", (3, 4))], epoch=100)
    st.commit_epoch(100)
    st.ingest_batch(1, [(b"a", None), (b"c", (5, 6))], epoch=200)
    st.commit_epoch(200)

    st2 = SpillStateStore(d)
    assert st2.committed_epoch == 200
    assert st2.get(1, b"a") is None
    assert st2.get(1, b"b") == (3, 4)
    assert st2.get(1, b"c") == (5, 6)


def test_uncommitted_epoch_lost_on_reopen(tmp_path):
    d = str(tmp_path)
    st = SpillStateStore(d)
    st.ingest_batch(1, [(b"a", (1,))], epoch=100)
    st.commit_epoch(100)
    st.ingest_batch(1, [(b"b", (2,))], epoch=200)  # never committed
    st2 = SpillStateStore(d)
    assert st2.get(1, b"a") == (1,)
    assert st2.get(1, b"b") is None  # checkpoint semantics: gone


def test_compaction_keeps_data_and_prunes_files(tmp_path):
    d = str(tmp_path)
    st = SpillStateStore(d)
    # enough commits that pre-compaction runs also AGE OUT of the
    # time-travel retention window (HISTORY_VERSIONS manifests)
    for e in range(1, 24):
        st.ingest_batch(3, [(f"k{e}".encode(), (e,))], epoch=e * 10)
        st.commit_epoch(e * 10)
    runs = os.listdir(os.path.join(d, "runs"))
    assert len([r for r in runs if r.startswith("t3_")]) < 23  # pruned
    st2 = SpillStateStore(d)
    assert st2.table_len(3) == 23
    for e in range(1, 24):
        assert st2.get(3, f"k{e}".encode()) == (e,)


def test_agg_job_recovery_over_spill_store(tmp_path):
    """Kill a streaming agg job; a fresh process picks up from the committed
    epoch with identical MV contents (SURVEY §5 checkpoint/resume)."""
    d = str(tmp_path)

    def build_job(store, chunks):
        inj = BarrierInjector()
        src = SourceExecutor(S, ListReader(chunks), inj)
        agg_state = StateTable(store, 10, [T.INT64, T.BYTEA], [0])
        agg = HashAggExecutor(src, [0], [AggCall("count"),
                                         AggCall("sum", _v())],
                              state_table=agg_state)
        mv = StateTable(store, 11, agg.schema.dtypes, [0])
        mat = MaterializeExecutor(agg, mv, ConflictBehavior.OVERWRITE)
        return StreamJob(mat, inj, store), mv

    def _v():
        from risingwave_tpu.expr import InputRef
        return InputRef(1, T.INT64)

    c1 = StreamChunk.from_rows(S.dtypes, [(Op.INSERT, (1, 10)),
                                          (Op.INSERT, (2, 20))])
    c2 = StreamChunk.from_rows(S.dtypes, [(Op.INSERT, (1, 5))])

    store = SpillStateStore(d)
    job, _ = build_job(store, [c1])
    job.run_until_idle()
    del store, job  # "crash"

    store2 = SpillStateStore(d)
    job2, mv = build_job(store2, [c2])
    job2.run_until_idle()
    rows = sorted(BatchScan(mv, None).rows())
    assert rows == [(1, 2, 15), (2, 1, 20)]


def test_state_larger_than_cache(tmp_path):
    """Point + range reads on a table far larger than the block cache:
    reads hit disk through the block index; the in-memory working set stays
    bounded (VERDICT r1 item 7 — state > RAM must work)."""
    from risingwave_tpu.state.hummock import BLOCK_ROWS

    d = str(tmp_path)
    st = SpillStateStore(d, cache_blocks=4)  # cache = 4 blocks (~1k rows)
    n = BLOCK_ROWS * 40  # ~10k rows across several commits
    per_commit = n // 4
    for c in range(4):
        batch = [(b"k%08d" % i, (i, i * 2))
                 for i in range(c * per_commit, (c + 1) * per_commit)]
        st.ingest_batch(7, batch, epoch=(c + 1) * 10)
        st.commit_epoch((c + 1) * 10)
    # reopen: recovery must NOT materialize the table
    st2 = SpillStateStore(d, cache_blocks=4)
    assert len(st2.cache) == 0  # nothing loaded yet
    # point reads all over the key space
    for i in [0, 1, per_commit - 1, per_commit, n // 2, n - 1]:
        assert st2.get(7, b"k%08d" % i) == (i, i * 2)
    assert st2.get(7, b"k%08d" % n) is None
    assert len(st2.cache) <= 4  # bounded working set
    # range read across a commit boundary
    lo, hi = per_commit - 5, per_commit + 5
    got = list(st2.iter_range(7, b"k%08d" % lo, b"k%08d" % hi))
    assert [k for k, _ in got] == [b"k%08d" % i for i in range(lo, hi)]
    assert len(st2.cache) <= 4
    # full scan streams correctly
    assert sum(1 for _ in st2.iter_range(7, None, None)) == n


def test_overwrites_and_tombstones_across_runs(tmp_path):
    """Newest run wins per key; tombstones shadow older runs and drop out
    at compaction."""
    d = str(tmp_path)
    st = SpillStateStore(d)
    st.ingest_batch(5, [(b"a", (1,)), (b"b", (1,)), (b"c", (1,))], epoch=10)
    st.commit_epoch(10)
    st.ingest_batch(5, [(b"a", (2,)), (b"b", None)], epoch=20)
    st.commit_epoch(20)
    assert st.get(5, b"a") == (2,)
    assert st.get(5, b"b") is None
    assert [k for k, _ in st.iter_range(5, None, None)] == [b"a", b"c"]
    # uncommitted delta overlays committed runs (shared-buffer read)
    st.ingest_batch(5, [(b"a", None), (b"d", (9,))], epoch=30)
    assert st.get(5, b"a") is None
    assert st.get(5, b"d") == (9,)
    assert [k for k, _ in st.iter_range(5, None, None)] == [b"c", b"d"]
    # ...but vanishes on crash (not committed)
    st2 = SpillStateStore(d)
    assert st2.get(5, b"a") == (2,)
    assert st2.get(5, b"d") is None
