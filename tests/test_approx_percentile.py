"""approx_percentile: ordered-set syntax, log-bucket accuracy bound,
retraction, negative/zero values, grouping, recovery.

Reference: `src/stream/src/executor/approx_percentile/` (bucket =
ceil(log_base |v|), base = (1+e)/(1-e), output walk neg desc -> zeros ->
pos asc, value = ±2·base^i/(base+1));
`binder/expr/function/aggregate.rs:183` (direct-arg validation).
"""
import pytest

from risingwave_tpu.expr.agg import AggCall, ApproxPercentileState, \
    create_agg_state
from risingwave_tpu.sql import Database


def ticks(db, n=3):
    for _ in range(n):
        db.tick()


class TestState:
    def test_accuracy_bound(self):
        st = ApproxPercentileState(0.5, 0.01)
        for v in range(1, 1001):
            st.apply(1, v)
        assert abs(st.output() - 500) / 500 <= 0.02

    def test_retraction(self):
        st = ApproxPercentileState(0.5, 0.01)
        for v in range(1, 101):
            st.apply(1, v)
        for v in range(51, 101):
            st.apply(-1, v)
        assert abs(st.output() - 25) / 25 <= 0.03
        for v in range(1, 51):
            st.apply(-1, v)
        assert st.output() is None

    def test_negatives_zeros_and_extremes(self):
        st = ApproxPercentileState(0.5, 0.01)
        for v in (-100, -10, 0, 0, 10, 100):
            st.apply(1, v)
        assert st.output() == 0.0
        lo = ApproxPercentileState(0.0, 0.01)
        hi = ApproxPercentileState(1.0, 0.01)
        for v in (-100, -10, 0, 10, 100):
            lo.apply(1, v)
            hi.apply(1, v)
        assert abs(lo.output() + 100) / 100 <= 0.02
        assert abs(hi.output() - 100) / 100 <= 0.02

    def test_invalid_args(self):
        with pytest.raises(ValueError):
            ApproxPercentileState(1.5, 0.01)
        with pytest.raises(ValueError):
            ApproxPercentileState(0.5, 0.0)

    def test_factory_defaults(self):
        st = create_agg_state(AggCall("approx_percentile",
                                      direct_args=(0.9, 0.05)))
        assert st.quantile == 0.9


class TestSql:
    def test_grouped_with_retraction(self):
        db = Database()
        db.run("CREATE TABLE t (k BIGINT, v DOUBLE PRECISION)")
        db.run("CREATE MATERIALIZED VIEW m AS SELECT k,"
               " approx_percentile(0.5, 0.01) WITHIN GROUP (ORDER BY v)"
               " AS p FROM t GROUP BY k")
        db.run("INSERT INTO t VALUES "
               + ", ".join(f"(1, {v})" for v in range(1, 101)) + ", "
               + ", ".join(f"(2, {v})" for v in range(1, 11)))
        ticks(db)
        rows = dict(db.query("SELECT * FROM m"))
        assert abs(rows[1] - 50) / 50 <= 0.03
        assert abs(rows[2] - 5) / 5 <= 0.03
        db.run("DELETE FROM t WHERE k = 1 AND v > 50")
        ticks(db)
        rows = dict(db.query("SELECT * FROM m"))
        assert abs(rows[1] - 25) / 25 <= 0.05

    def test_requires_within_group(self):
        db = Database()
        db.run("CREATE TABLE t (v BIGINT)")
        with pytest.raises(ValueError, match="WITHIN GROUP"):
            db.run("CREATE MATERIALIZED VIEW m AS SELECT"
                   " approx_percentile(0.5, 0.01) FROM t")

    def test_direct_args_must_be_constant(self):
        db = Database()
        db.run("CREATE TABLE t (v DOUBLE PRECISION)")
        with pytest.raises(ValueError, match="constant"):
            db.run("CREATE MATERIALIZED VIEW m AS SELECT"
                   " approx_percentile(v, 0.01) WITHIN GROUP (ORDER BY v)"
                   " FROM t")

    def test_recovery(self, tmp_path):
        d = str(tmp_path / "db")
        db = Database(data_dir=d)
        db.run("CREATE TABLE t (v DOUBLE PRECISION)")
        db.run("CREATE MATERIALIZED VIEW m AS SELECT"
               " approx_percentile(0.5, 0.01) WITHIN GROUP (ORDER BY v)"
               " AS p FROM t")
        db.run("INSERT INTO t VALUES "
               + ", ".join(f"({v})" for v in range(1, 101)))
        ticks(db)
        before = db.query("SELECT * FROM m")
        del db
        db2 = Database(data_dir=d)
        ticks(db2)
        assert db2.query("SELECT * FROM m") == before
        db2.run("DELETE FROM t WHERE v > 50")
        ticks(db2)
        assert abs(db2.query("SELECT * FROM m")[0][0] - 25) / 25 <= 0.05
