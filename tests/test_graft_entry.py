"""Driver-contract regression tests for __graft_entry__.py.

The driver validates multi-chip sharding by calling ``dryrun_multichip(N)``
in its own process, in an environment whose *default* JAX platform is the
real-TPU axon tunnel. Rounds 1 and 2 both failed that gate on environment
details the in-process test suite (conftest pins CPU up front) could never
see. So these tests run the entry points in **fresh subprocesses** that
deliberately do NOT pre-pin the platform — the entry must pin CPU itself.
"""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_fresh(code: str, extra_env: dict | None = None, timeout: int = 600):
    env = os.environ.copy()
    # Simulate the driver: no conftest, no pre-pinned CPU platform and no
    # forced host device count. (We cannot re-create the axon tunnel here,
    # but we can verify the entry pins the platform itself rather than
    # relying on the caller's env.)
    env.pop("JAX_PLATFORMS", None)
    env.pop("JAX_PLATFORM_NAME", None)
    env["XLA_FLAGS"] = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    if extra_env:
        env.update(extra_env)
    return subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=timeout)


def test_dryrun_multichip_fresh_subprocess():
    r = _run_fresh(
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(8)\n"
        "import jax\n"
        "assert jax.default_backend() == 'cpu', jax.default_backend()\n"
        "print('DRYRUN_OK')\n")
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "DRYRUN_OK" in r.stdout


def test_dryrun_after_entry_same_process():
    """The driver may compile-check entry() then dry-run in one process;
    dryrun_multichip must rebuild backends onto CPU in that case."""
    r = _run_fresh(
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"  # entry() itself needs a backend here
        "import jax\n"
        "import __graft_entry__ as g\n"
        "fn, args = g.entry()\n"
        "jax.jit(fn).lower(*args)\n"  # touches/initializes the backend
        "g.dryrun_multichip(8)\n"
        "assert len(jax.devices('cpu')) >= 8\n"
        "print('DRYRUN_OK')\n")
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "DRYRUN_OK" in r.stdout


def test_entry_compiles_fresh_subprocess():
    r = _run_fresh(
        "import os\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "import jax\n"
        "import __graft_entry__ as g\n"
        "fn, args = g.entry()\n"
        "out = jax.jit(fn)(*args)\n"
        "jax.block_until_ready(out)\n"
        "print('ENTRY_OK')\n")
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "ENTRY_OK" in r.stdout
