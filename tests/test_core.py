"""L0 kernel tests: chunks, vnode hashing, epochs, encodings."""
import zlib

import numpy as np
import pytest

from risingwave_tpu.core import (
    Column, DataChunk, Op, StreamChunk, StreamChunkBuilder, compute_vnodes,
    dtypes as T, now_epoch, to_device_chunk, vnode_of_row,
)
from risingwave_tpu.core.encoding import (
    SortKey, decode_row, encode_datum_memcomparable, encode_key, encode_row,
)
from risingwave_tpu.core.epoch import EpochPair, epoch_from_physical, physical_time_ms
from risingwave_tpu.core.vnode import (
    column_hash64, crc32_bytes_matrix, hash_columns64,
)


class TestChunk:
    def test_column_nulls(self):
        c = Column.from_list(T.INT64, [1, None, 3])
        assert c.to_list() == [1, None, 3]
        assert list(c.validity) == [True, False, True]

    def test_varchar_column(self):
        c = Column.from_list(T.VARCHAR, ["a", None, "ccc"])
        assert c.to_list() == ["a", None, "ccc"]

    def test_datachunk_rows_visibility(self):
        ch = DataChunk.from_rows([T.INT64, T.VARCHAR],
                                 [(1, "a"), (2, "b"), (3, "c")])
        assert ch.cardinality == 3
        vis = ch.with_visibility(np.array([True, False, True]))
        assert vis.rows() == [(1, "a"), (3, "c")]
        assert vis.compact().cardinality == 2

    def test_stream_chunk_ops_signs(self):
        ch = StreamChunk.from_rows(
            [T.INT64],
            [(Op.INSERT, (1,)), (Op.DELETE, (2,)),
             (Op.UPDATE_DELETE, (3,)), (Op.UPDATE_INSERT, (4,))])
        assert list(ch.signs()) == [1, -1, -1, 1]
        assert ch.op_rows()[1] == (Op.DELETE, (2,))

    def test_builder_update_pair_not_split(self):
        b = StreamChunkBuilder([T.INT64], max_chunk_size=2)
        b.append_row(Op.INSERT, (1,))
        # U- at the boundary must NOT flush until U+ arrives
        b.append_row(Op.UPDATE_DELETE, (2,))
        b.append_row(Op.UPDATE_INSERT, (3,))
        chunks = b.drain()
        assert [c.capacity for c in chunks] == [3]

    def test_builder_no_row_loss_on_overflow(self):
        b = StreamChunkBuilder([T.INT64], max_chunk_size=4)
        for i in range(10):
            b.append_row(Op.INSERT, (i,))
        chunks = b.drain()
        assert sum(c.capacity for c in chunks) == 10
        got = [r[0] for c in chunks for _, r in c.op_rows()]
        assert got == list(range(10))
        assert b.drain() == []

    def test_device_chunk_padding(self):
        ch = StreamChunk.from_rows([T.INT64, T.VARCHAR],
                                   [(Op.INSERT, (7, "x")), (Op.DELETE, (8, "y"))])
        d = to_device_chunk(ch)
        assert d.capacity == 16 and d.n_rows == 2
        assert d.cols[0].shape == (16,)
        assert list(np.asarray(d.mask))[:3] == [True, True, False]
        assert list(np.asarray(d.signs))[:3] == [1, -1, 0]


class TestVnode:
    def test_crc32_matrix_matches_zlib(self):
        rows = np.frombuffer(b"hello123worldxyz", dtype=np.uint8).reshape(2, 8)
        out = crc32_bytes_matrix(rows)
        assert out[0] == zlib.crc32(b"hello123")
        assert out[1] == zlib.crc32(b"worldxyz")

    def test_vectorized_matches_scalar_int(self):
        col = Column.from_list(T.INT64, [0, 1, -5, 123456789, None])
        vn = compute_vnodes([col])
        for i, v in enumerate([0, 1, -5, 123456789, None]):
            assert vn[i] == vnode_of_row([v])

    def test_vectorized_matches_scalar_str(self):
        col = Column.from_list(T.VARCHAR, ["alpha", "beta", None])
        vn = compute_vnodes([col])
        for i, v in enumerate(["alpha", "beta", None]):
            assert vn[i] == vnode_of_row([v])

    def test_multicolumn(self):
        c1 = Column.from_list(T.INT64, [1, 2])
        c2 = Column.from_list(T.VARCHAR, ["a", "b"])
        vn = compute_vnodes([c1, c2])
        assert vn[0] == vnode_of_row([1, "a"])
        assert vn[1] == vnode_of_row([2, "b"])

    def test_bool_float_parity(self):
        cb = Column.from_list(T.BOOLEAN, [True, False])
        vnb = compute_vnodes([cb])
        assert vnb[0] == vnode_of_row([True])
        assert vnb[1] == vnode_of_row([False])
        cf = Column.from_list(T.FLOAT64, [1.5, -0.0])
        vnf = compute_vnodes([cf])
        assert vnf[0] == vnode_of_row([1.5])
        assert vnf[1] == vnode_of_row([0.0])  # -0.0 == 0.0 must agree

    def test_device_crc_matches_host(self):
        from risingwave_tpu.core.vnode import compute_vnodes_jnp
        col = Column.from_list(T.INT64, [0, 42, -7, 999999])
        host = compute_vnodes([col])
        dev = np.asarray(compute_vnodes_jnp(np.array([0, 42, -7, 999999],
                                                     dtype=np.int64)))
        assert list(host) == list(dev)

    def test_hash64_null_aware(self):
        c1 = Column.from_list(T.INT64, [1, None])
        c2 = Column.from_list(T.INT64, [1, None])
        assert list(column_hash64(c1)) == list(column_hash64(c2))
        h = hash_columns64([c1, Column.from_list(T.VARCHAR, ["x", "y"])])
        assert len(h) == 2 and h[0] != h[1]


class TestEpoch:
    def test_epoch_roundtrip(self):
        e = epoch_from_physical(1234567, 3)
        assert physical_time_ms(e) == 1234567
        assert e & 0xFFFF == 3

    def test_monotonic(self):
        e1 = now_epoch()
        e2 = now_epoch(e1)
        assert e2 > e1

    def test_pair(self):
        p = EpochPair.new_initial(100 << 16)
        p2 = p.next(200 << 16)
        assert p2.prev == p.curr


class TestEncoding:
    def test_memcomparable_int_order(self):
        vals = [-100, -1, 0, 1, 100, None]
        encs = [encode_datum_memcomparable(v, T.INT64) for v in vals]
        assert encs == sorted(encs)  # nulls last under ASC

    def test_memcomparable_desc(self):
        vals = [3, 1, 2]
        encs = {v: encode_datum_memcomparable(v, T.INT32, desc=True) for v in vals}
        assert encs[3] < encs[2] < encs[1]

    def test_memcomparable_float_order(self):
        vals = [-1.5, -0.5, 0.0, 0.25, 2.0]
        encs = [encode_datum_memcomparable(v, T.FLOAT64) for v in vals]
        assert encs == sorted(encs)

    def test_memcomparable_string_prefix(self):
        a = encode_datum_memcomparable("ab", T.VARCHAR)
        b = encode_datum_memcomparable("abc", T.VARCHAR)
        c = encode_datum_memcomparable("ac", T.VARCHAR)
        assert a < b < c

    def test_value_roundtrip(self):
        from decimal import Decimal
        dtypes = [T.INT64, T.VARCHAR, T.FLOAT64, T.BOOLEAN, T.DECIMAL, T.TIMESTAMP]
        row = (42, "hello", 3.5, True, Decimal("1.25"), 1700000000000000)
        buf = encode_row(row, dtypes)
        assert decode_row(buf, dtypes) == row

    def test_value_roundtrip_nulls(self):
        dtypes = [T.INT64, T.VARCHAR]
        assert decode_row(encode_row((None, None), dtypes), dtypes) == (None, None)

    def test_sort_key_mixed(self):
        dtypes = [T.INT64, T.VARCHAR]
        rows = [(1, "b"), (1, "a"), (0, "z"), (2, None)]
        ordered = sorted(rows, key=lambda r: SortKey(r, dtypes))
        assert ordered == [(0, "z"), (1, "a"), (1, "b"), (2, None)]
