"""Run the .slt end-to-end suites (the reference's e2e_test tier)."""
import glob
import os

import pytest

from risingwave_tpu.testing import run_slt_file

E2E = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "e2e_test")
SLT_FILES = sorted(glob.glob(os.path.join(E2E, "**", "*.slt"), recursive=True))


@pytest.mark.parametrize("device", ["off", "on"])
@pytest.mark.parametrize("path", SLT_FILES,
                         ids=[os.path.relpath(p, E2E) for p in SLT_FILES])
def test_slt(path, device):
    """The whole e2e suite must pass identically with the TPU dispatch seam
    on — same SQL, same results, device HashAgg under eligible fragments."""
    from risingwave_tpu.sql import Database
    run_slt_file(path, db=Database(device=device))


def test_mv_equals_batch_recompute_nexmark_datagen():
    """Parity oracle on generated data: every MV == batch recompute of its
    defining query over the base table (SURVEY §4 'core correctness
    oracle')."""
    from risingwave_tpu.sql import Database
    db = Database()
    db.run("CREATE SOURCE nbid (auction BIGINT, bidder BIGINT, price BIGINT, "
           "channel VARCHAR, url VARCHAR, date_time TIMESTAMP, extra VARCHAR)"
           " WITH (connector='nexmark', nexmark.table='bid', "
           "nexmark.max.events='2000')")
    # sources are unmaterialized streams (source_executor.rs): batch
    # queries go through an MV materializing the rows, not the source
    db.run("CREATE MATERIALIZED VIEW raw AS SELECT * FROM nbid")
    db.run("CREATE MATERIALIZED VIEW agg AS SELECT auction, count(*) AS c, "
           "sum(price) AS s, max(price) AS m FROM nbid GROUP BY auction")
    db.run("FLUSH")
    db.run("FLUSH")
    mv = sorted(db.query("SELECT * FROM agg"))
    batch = sorted(db.query(
        "SELECT auction, count(*), sum(price), max(price) "
        "FROM raw GROUP BY auction"))
    assert mv == batch and len(mv) > 10
