"""Planner wiring of the executor inventory (VERDICT r02 item 3): UNION,
DISTINCT dedup, changelog, NOW() temporal filters, EOWC Sort, and the
Dispatch/Merge exchange — each reachable from SQL, each surviving
DDL-replay recovery."""
import pytest

from risingwave_tpu.sql import Database


def test_union_all_type_mismatch_rejected():
    db = Database()
    db.run("CREATE TABLE a (k INT, s VARCHAR)")
    db.run("CREATE TABLE b (k INT, v INT)")
    with pytest.raises(ValueError, match="cannot be matched"):
        db.run("CREATE MATERIALIZED VIEW u AS "
               "SELECT s FROM a UNION ALL SELECT v FROM b")


def test_union_all_column_count_mismatch_rejected():
    db = Database()
    db.run("CREATE TABLE a (k INT)")
    db.run("CREATE TABLE b (k INT, v INT)")
    with pytest.raises(ValueError, match="same number"):
        db.run("SELECT k FROM a UNION ALL SELECT k, v FROM b")


def test_union_all_recovery(tmp_path):
    d = str(tmp_path)
    db = Database(data_dir=d)
    db.run("CREATE TABLE a (k INT, v INT)")
    db.run("CREATE TABLE b (k INT, v INT)")
    db.run("CREATE MATERIALIZED VIEW u AS "
           "SELECT k, v FROM a UNION ALL SELECT k, v FROM b")
    db.run("INSERT INTO a VALUES (1, 10)")
    db.run("INSERT INTO b VALUES (1, 10), (2, 20)")
    db.run("FLUSH")
    before = sorted(db.query("SELECT * FROM u"))
    assert before == [(1, 10), (1, 10), (2, 20)]
    db2 = Database(data_dir=d)
    assert sorted(db2.query("SELECT * FROM u")) == before
    db2.run("DELETE FROM b WHERE k = 1")
    db2.run("FLUSH")
    assert sorted(db2.query("SELECT * FROM u")) == [(1, 10), (2, 20)]


def test_union_constant_branches():
    db = Database()
    assert sorted(db.query("SELECT 1 UNION SELECT 2")) == [(1,), (2,)]
    assert sorted(db.query("SELECT 1 UNION ALL SELECT 1")) == [(1,), (1,)]
    db.run("CREATE TABLE t (a INT)")
    db.run("INSERT INTO t VALUES (1), (2)")
    db.run("CREATE MATERIALIZED VIEW cm AS "
           "SELECT a FROM t UNION ALL SELECT 99")
    db.run("FLUSH")
    assert sorted(db.query("SELECT * FROM cm")) == [(1,), (2,), (99,)]
    db.run("DELETE FROM t WHERE a = 2")
    db.run("FLUSH")
    assert sorted(db.query("SELECT * FROM cm")) == [(1,), (99,)]


def test_union_order_limit_applies_to_whole_set():
    db = Database()
    db.run("CREATE TABLE t (a INT)")
    db.run("CREATE TABLE u (a INT)")
    db.run("INSERT INTO t VALUES (1), (2), (3)")
    db.run("INSERT INTO u VALUES (10), (20)")
    assert db.query("SELECT a FROM t UNION ALL SELECT a FROM u "
                    "ORDER BY a LIMIT 2") == [(1,), (2,)]
    with pytest.raises(ValueError, match="parenthesized"):
        db.query("SELECT a FROM t ORDER BY a UNION ALL SELECT a FROM u")
    # streaming: TopN over the union, retraction-correct
    db.run("CREATE MATERIALIZED VIEW m AS SELECT a FROM t "
           "UNION ALL SELECT a FROM u ORDER BY a LIMIT 2")
    db.run("FLUSH")
    assert sorted(db.query("SELECT * FROM m")) == [(1,), (2,)]
    db.run("DELETE FROM t WHERE a = 2")
    db.run("FLUSH")
    assert sorted(db.query("SELECT * FROM m")) == [(1,), (3,)]


def test_parallelism_pin_does_not_leak_into_new_session(tmp_path):
    d = str(tmp_path)
    db = Database(data_dir=d)
    db.run("CREATE TABLE t (k INT, v INT)")
    db.run("SET streaming_parallelism TO 4")
    db.run("CREATE MATERIALIZED VIEW agg AS SELECT k, count(*) AS c "
           "FROM t GROUP BY k")
    db2 = Database(data_dir=d)
    assert int(db2.session_vars.get("streaming_parallelism") or 0) == 0


def test_union_distinct_cross_branch_dedup_retraction():
    db = Database()
    db.run("CREATE TABLE a (v INT)")
    db.run("CREATE TABLE b (v INT)")
    db.run("CREATE MATERIALIZED VIEW u AS "
           "SELECT v FROM a UNION SELECT v FROM b")
    db.run("INSERT INTO a VALUES (1)")
    db.run("INSERT INTO b VALUES (1)")
    db.run("FLUSH")
    assert db.query("SELECT * FROM u") == [(1,)]
    # dropping one branch's copy keeps the value (still present in a)
    db.run("DELETE FROM b WHERE v = 1")
    db.run("FLUSH")
    assert db.query("SELECT * FROM u") == [(1,)]
    db.run("DELETE FROM a WHERE v = 1")
    db.run("FLUSH")
    assert db.query("SELECT * FROM u") == []


def test_distinct_append_only_plans_dedup():
    db = Database()
    db.run("CREATE SOURCE s (v BIGINT, extra VARCHAR) WITH "
           "(connector='datagen', fields.v.kind='sequence', "
           "fields.v.start='1', fields.v.end='6', datagen.rows.per.second='6')")
    db.run("CREATE MATERIALIZED VIEW dv AS SELECT DISTINCT v FROM s")
    e = db.catalog.get("dv").runtime["shared"].upstream
    names = set()
    stack = [e]
    while stack:
        x = stack.pop()
        names.add(type(x).__name__)
        for attr in ("input", "port"):
            c = getattr(x, attr, None)
            if c is not None:
                stack.append(c)
    assert "AppendOnlyDedupExecutor" in names


def test_changelog_recovery_and_join(tmp_path):
    d = str(tmp_path)
    db = Database(data_dir=d)
    db.run("CREATE TABLE t (k INT, v INT)")
    db.run("CREATE MATERIALIZED VIEW chg AS "
           "WITH s AS changelog FROM t SELECT k, v, changelog_op FROM s")
    db.run("INSERT INTO t VALUES (1, 5)")
    db.run("UPDATE t SET v = 6 WHERE k = 1")
    db.run("FLUSH")
    rows = sorted(db.query("SELECT * FROM chg"))
    assert rows == [(1, 5, 1), (1, 5, 4), (1, 6, 3)]
    db2 = Database(data_dir=d)
    assert sorted(db2.query("SELECT * FROM chg")) == rows


def test_now_dynamic_filter_moves_bound():
    from datetime import datetime, timezone
    import time
    db = Database()
    db.run("CREATE TABLE ev (k INT, ts TIMESTAMP)")
    now_us = int(time.time() * 1_000_000)
    f = lambda us: datetime.fromtimestamp(
        us / 1e6, tz=timezone.utc).strftime("%Y-%m-%d %H:%M:%S")
    old, fut = now_us - 3600_000_000, now_us + 3600_000_000
    db.run(f"INSERT INTO ev VALUES (1, CAST('{f(old)}' AS TIMESTAMP)), "
           f"(2, CAST('{f(fut)}' AS TIMESTAMP))")
    db.run("CREATE MATERIALIZED VIEW recent AS SELECT k FROM ev "
           "WHERE ts > NOW() - INTERVAL '600' SECOND")
    db.run("FLUSH")
    assert db.query("SELECT * FROM recent") == [(2,)]
    # rows arriving later still filter against the advancing bound
    db.run(f"INSERT INTO ev VALUES (3, CAST('{f(old)}' AS TIMESTAMP))")
    db.run("FLUSH")
    assert db.query("SELECT * FROM recent") == [(2,)]


def test_now_rejected_outside_where():
    db = Database()
    db.run("CREATE TABLE t (k INT)")
    with pytest.raises(Exception):
        db.run("CREATE MATERIALIZED VIEW x AS SELECT now() FROM t")


def test_eowc_sort_recovery(tmp_path):
    d = str(tmp_path)
    db = Database(data_dir=d)
    db.run("CREATE TABLE sev (k INT, ts TIMESTAMP, "
           "WATERMARK FOR ts AS ts - INTERVAL '2' SECOND)")
    db.run("CREATE MATERIALIZED VIEW o AS SELECT ts, k FROM sev "
           "EMIT ON WINDOW CLOSE")
    db.run("INSERT INTO sev VALUES (3, CAST('2024-01-01 00:00:03' AS "
           "TIMESTAMP)), (1, CAST('2024-01-01 00:00:01' AS TIMESTAMP))")
    db.run("FLUSH")
    assert [r[1] for r in db.query("SELECT * FROM o")] == [1]
    # the 3s row is buffered in Sort state; recovery must keep it pending
    db2 = Database(data_dir=d)
    assert [r[1] for r in db2.query("SELECT * FROM o")] == [1]
    db2.run("INSERT INTO sev VALUES (9, CAST('2024-01-01 00:00:09' AS "
            "TIMESTAMP))")
    db2.run("FLUSH")
    assert sorted(r[1] for r in db2.query("SELECT * FROM o")) == [1, 3]


def test_parallel_agg_parity_and_recovery(tmp_path):
    d = str(tmp_path)
    db = Database(data_dir=d)
    db.run("CREATE TABLE t (k INT, v INT)")
    db.run("SET streaming_parallelism TO 3")
    db.run("CREATE MATERIALIZED VIEW agg AS SELECT k, count(*) AS c, "
           "sum(v) AS s, min(v) AS mn, max(v) AS mx FROM t GROUP BY k")
    db.run("SET streaming_parallelism TO 0")
    from risingwave_tpu.ops import MergeExecutor
    mat = db.catalog.get("agg").runtime["shared"].upstream
    assert isinstance(mat.input.input, MergeExecutor)
    rows = [(k % 7, k * 3 % 11) for k in range(50)]
    db.run("INSERT INTO t VALUES " +
           ", ".join(f"({a}, {b})" for a, b in rows))
    db.run("UPDATE t SET v = 99 WHERE k = 3")
    db.run("DELETE FROM t WHERE k = 5")
    db.run("FLUSH")
    got = sorted(db.query("SELECT * FROM agg"))
    want = sorted(db.query("SELECT k, count(*), sum(v), min(v), max(v) "
                           "FROM t GROUP BY k"))
    assert got == want and len(got) == 6
    # recovery replans with the logged parallelism and reloads state
    db2 = Database(data_dir=d)
    mat2 = db2.catalog.get("agg").runtime["shared"].upstream
    assert isinstance(mat2.input.input, MergeExecutor)
    assert sorted(db2.query("SELECT * FROM agg")) == got
