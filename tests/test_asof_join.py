"""ASOF join: SQL surface, best-match selection, displacement on better
matches, left-outer NULL padding, recovery.

Reference: `src/stream/src/executor/asof_join.rs` (match = closest right
row satisfying the single inequality, per equi key; a newly arrived
better match displaces the emitted pair), `parser.rs:5012` (ASOF / ASOF
LEFT JOIN syntax).
"""
from risingwave_tpu.sql import Database


def nsort(rows):
    return sorted(rows, key=lambda r: tuple((v is None, v) for v in r))


def ticks(db, n=3):
    for _ in range(n):
        db.tick()


def mk(sql_mv):
    db = Database()
    db.run("CREATE TABLE trades (tk VARCHAR, tt BIGINT, qty BIGINT)")
    db.run("CREATE TABLE quotes (qk VARCHAR, qt BIGINT, px BIGINT)")
    db.run(sql_mv)
    return db


ASOF_INNER = ("CREATE MATERIALIZED VIEW m AS SELECT tk, tt, qty, qt, px "
              "FROM trades ASOF JOIN quotes "
              "ON tk = qk AND tt >= qt")
ASOF_LEFT = ("CREATE MATERIALIZED VIEW m AS SELECT tk, tt, qty, qt, px "
             "FROM trades ASOF LEFT JOIN quotes "
             "ON tk = qk AND tt >= qt")


class TestAsOfInner:
    def test_picks_latest_quote_at_or_before(self):
        db = mk(ASOF_INNER)
        db.run("INSERT INTO quotes VALUES ('a', 10, 100), ('a', 20, 200),"
               " ('a', 30, 300)")
        db.run("INSERT INTO trades VALUES ('a', 25, 1)")
        ticks(db)
        assert db.query("SELECT * FROM m") == [("a", 25, 1, 20, 200)]

    def test_no_match_emits_nothing(self):
        db = mk(ASOF_INNER)
        db.run("INSERT INTO quotes VALUES ('a', 50, 500)")
        db.run("INSERT INTO trades VALUES ('a', 25, 1), ('b', 99, 2)")
        ticks(db)
        assert db.query("SELECT * FROM m") == []

    def test_better_quote_displaces_match(self):
        db = mk(ASOF_INNER)
        db.run("INSERT INTO trades VALUES ('a', 25, 1)")
        db.run("INSERT INTO quotes VALUES ('a', 10, 100)")
        ticks(db)
        assert db.query("SELECT * FROM m") == [("a", 25, 1, 10, 100)]
        # closer quote arrives -> the emitted pair is displaced
        db.run("INSERT INTO quotes VALUES ('a', 20, 200)")
        ticks(db)
        assert db.query("SELECT * FROM m") == [("a", 25, 1, 20, 200)]
        # deleting it falls back to the previous best
        db.run("DELETE FROM quotes WHERE qt = 20")
        ticks(db)
        assert db.query("SELECT * FROM m") == [("a", 25, 1, 10, 100)]

    def test_trade_delete_retracts(self):
        db = mk(ASOF_INNER)
        db.run("INSERT INTO quotes VALUES ('a', 10, 100)")
        db.run("INSERT INTO trades VALUES ('a', 25, 1)")
        ticks(db)
        db.run("DELETE FROM trades WHERE tt = 25")
        ticks(db)
        assert db.query("SELECT * FROM m") == []

    def test_strict_inequality(self):
        db = mk("CREATE MATERIALIZED VIEW m AS SELECT tk, tt, qt "
                "FROM trades ASOF JOIN quotes ON tk = qk AND tt > qt")
        db.run("INSERT INTO quotes VALUES ('a', 25, 1), ('a', 10, 2)")
        db.run("INSERT INTO trades VALUES ('a', 25, 9)")
        ticks(db)
        # tt > qt excludes the equal quote; best below is 10
        assert db.query("SELECT * FROM m") == [("a", 25, 10)]

    def test_forward_direction(self):
        db = mk("CREATE MATERIALIZED VIEW m AS SELECT tk, tt, qt "
                "FROM trades ASOF JOIN quotes ON tk = qk AND tt <= qt")
        db.run("INSERT INTO quotes VALUES ('a', 40, 1), ('a', 30, 2),"
               " ('a', 10, 3)")
        db.run("INSERT INTO trades VALUES ('a', 25, 9)")
        ticks(db)
        # smallest quote time >= 25
        assert db.query("SELECT * FROM m") == [("a", 25, 30)]


class TestAsOfLeft:
    def test_null_padding_then_match(self):
        db = mk(ASOF_LEFT)
        db.run("INSERT INTO trades VALUES ('a', 25, 1)")
        ticks(db)
        assert db.query("SELECT * FROM m") == [("a", 25, 1, None, None)]
        db.run("INSERT INTO quotes VALUES ('a', 20, 200)")
        ticks(db)
        assert db.query("SELECT * FROM m") == [("a", 25, 1, 20, 200)]
        db.run("DELETE FROM quotes WHERE qt = 20")
        ticks(db)
        assert db.query("SELECT * FROM m") == [("a", 25, 1, None, None)]

    def test_multiple_keys_and_trades(self):
        db = mk(ASOF_LEFT)
        db.run("INSERT INTO quotes VALUES ('a', 10, 100), ('b', 5, 50)")
        db.run("INSERT INTO trades VALUES ('a', 25, 1), ('b', 3, 2),"
               " ('c', 7, 3)")
        ticks(db)
        assert nsort(db.query("SELECT * FROM m")) == nsort([
            ("a", 25, 1, 10, 100),
            ("b", 3, 2, None, None),
            ("c", 7, 3, None, None)])


class TestAsOfRecovery:
    def test_state_survives_restart(self, tmp_path):
        d = str(tmp_path / "db")
        db = Database(data_dir=d)
        db.run("CREATE TABLE trades (tk VARCHAR, tt BIGINT, qty BIGINT)")
        db.run("CREATE TABLE quotes (qk VARCHAR, qt BIGINT, px BIGINT)")
        db.run(ASOF_INNER.replace("MATERIALIZED VIEW m",
                                  "MATERIALIZED VIEW m"))
        db.run("INSERT INTO quotes VALUES ('a', 10, 100)")
        db.run("INSERT INTO trades VALUES ('a', 25, 1)")
        ticks(db)
        assert db.query("SELECT * FROM m") == [("a", 25, 1, 10, 100)]
        del db
        db2 = Database(data_dir=d)
        ticks(db2)
        assert db2.query("SELECT * FROM m") == [("a", 25, 1, 10, 100)]
        # post-recovery the join keeps maintaining
        db2.run("INSERT INTO quotes VALUES ('a', 20, 200)")
        ticks(db2)
        assert db2.query("SELECT * FROM m") == [("a", 25, 1, 20, 200)]
