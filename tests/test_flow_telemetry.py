"""ISSUE 20 sensory plane: flow telemetry, pressure attribution, and
the flight recorder.

Acceptance contract under test: per-vnode TRAFFIC histograms are exact
(unique-key workload: traffic == occupancy per bucket, totals equal the
row count; an 8-shard run's psum'd totals equal the 1-shard run's
bit-for-bit); zipf flow over a spread key set reads as traffic-vs-
occupancy divergence while a unique-key flow reads 0; the PressureBoard
scalar decomposes into labeled contributions that recombine to the
global EXACTLY (by construction — `pressure_of` IS
`combine_contributions(attribution(db))`) under the slow-sink and
slow-worker failpoints; a seeded device fault auto-dumps a flight-
recorder bundle readable from the DEAD data dir via `risectl blackbox`;
`trace export` stays valid Chrome JSON with the new instant events; and
the unarmed path leaves no tv* slots or `flow` signature flag behind.
"""
import json
import os
import time

import numpy as np
import pytest

from risingwave_tpu.config import DeviceConfig, ROBUSTNESS
from risingwave_tpu.sql import Database
from risingwave_tpu.utils import failpoint as fp
from risingwave_tpu.utils.overload import PRESSURE

pytestmark = pytest.mark.telemetry

N = 4096
CHUNK = 32

BID_SRC = ("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           " nexmark.table='bid', nexmark.max.events='{n}',"
           " nexmark.chunk.size='{c}', nexmark.key.dist='{kd}')")
PERSON_SRC = ("CREATE SOURCE person (id BIGINT, name VARCHAR,"
              " email_address VARCHAR, credit_card VARCHAR, city VARCHAR,"
              " state VARCHAR, date_time TIMESTAMP, extra VARCHAR)"
              " WITH (connector='nexmark', nexmark.table='person',"
              " nexmark.max.events='{n}', nexmark.chunk.size='{c}')")
Q1_MV = ("CREATE MATERIALIZED VIEW q1a AS SELECT bidder,"
         " count(*) AS n, sum(price) AS dol, max(price) AS top"
         " FROM bid GROUP BY bidder")
PP_MV = ("CREATE MATERIALIZED VIEW pp AS SELECT id, count(*) AS c"
         " FROM person GROUP BY id")

_KNOBS = ("overload_window_s", "overload_high", "overload_low",
          "overload_hold_s", "serving_staleness_epochs",
          "exchange_credits")


@pytest.fixture(autouse=True)
def _clean():
    saved = {k: getattr(ROBUSTNESS, k) for k in _KNOBS}
    fp.reset()
    PRESSURE.reset()
    yield
    fp.reset()
    PRESSURE.reset()
    for k, v in saved.items():
        setattr(ROBUSTNESS, k, v)


def _arm_flow(monkeypatch, flow="1", skew="1", pre="0", hot="0", reb="0"):
    monkeypatch.setenv("RW_FLOW_STATS", flow)
    monkeypatch.setenv("RW_SKEW_STATS", skew)
    monkeypatch.setenv("RW_AGG_PRECOMBINE", pre)
    monkeypatch.setenv("RW_HOT_KEY_REP", hot)
    monkeypatch.setenv("RW_VNODE_REBALANCE", reb)


def _run(mv_sql, name, shards=1, srcs=(BID_SRC,), kd="zipf:4", n=N,
         capacity=2048, data_dir=None):
    db = Database(device=DeviceConfig(capacity=capacity,
                                      mesh_shards=shards,
                                      aot_compile=False,
                                      compile_buckets=0),
                  data_dir=data_dir)
    for s in srcs:
        db.run(s.format(n=n, c=CHUNK, kd=kd))
    db.run(mv_sql)
    job = db.catalog.get(name).runtime["fused_job"]
    assert job is not None, f"{name} must fuse"
    for _ in range(n // (64 * CHUNK) + 3):
        db.tick()
    job.sync()
    db.tick()
    return db, job


def _traffic(job, node_i):
    from risingwave_tpu.device.skew_stats import SK_BUCKETS
    st = job.program.node_stats(node_i, job._stat_totals)
    return [int(st.get(f"tv{b}", 0)) for b in range(SK_BUCKETS)]


def _flow_node(job):
    return next(i for i, nd in enumerate(job.program.nodes) if nd.flow)


# ---------------------------------------------------------------------------
# tentpole 1: traffic-per-vnode histograms
# ---------------------------------------------------------------------------


def test_traffic_histogram_exact_unique_keys(monkeypatch):
    """Unique group keys (person id): every routed row creates exactly
    one live key, so the traffic histogram must equal the occupancy
    histogram PER BUCKET and its total must equal the MV's row count —
    exact counts, hand-checkable against the MV itself. Unique keys
    also mean the flow goes exactly where the state lives: the
    traffic-vs-occupancy divergence must read 0."""
    from risingwave_tpu.device.skew_stats import SK_BUCKETS
    _arm_flow(monkeypatch)
    db, job = _run(PP_MV, "pp", srcs=(PERSON_SRC,), n=1024)
    i = _flow_node(job)
    tv = _traffic(job, i)
    st = job.program.node_stats(i, job._stat_totals)
    occ = [int(st[f"skv{b}"]) for b in range(SK_BUCKETS)]
    n_rows = len(db.query("SELECT * FROM pp"))
    assert n_rows > 0
    assert sum(tv) == n_rows, "every person row routed exactly once"
    assert tv == occ, "unique keys: traffic == occupancy per bucket"
    # the system-table surface carries the same numbers
    rows = db.query("SELECT * FROM rw_vnode_traffic WHERE job = 'pp'")
    vt = sorted(r for r in rows if r[3] == "vnode_traffic")
    assert [r[5] for r in vt] == tv
    assert abs(sum(r[6] for r in vt) - 1.0) < 1e-9   # shares sum to 1
    ts = [r for r in rows if r[3] == "traffic_skew"]
    assert len(ts) == 1 and ts[0][5] == sum(tv)
    div = [r for r in rows if r[3] == "traffic_div"]
    assert len(div) == 1 and div[0][6] == 0.0


def test_traffic_exact_through_precombine(monkeypatch):
    """The pre-combined agg path must weight each combined delta row by
    its raw-row count: the totals stay identical to the uncombined
    run — zipf keys so combining actually collapses rows."""
    _arm_flow(monkeypatch, pre="0")
    _, job_raw = _run(Q1_MV, "q1a")
    _arm_flow(monkeypatch, pre="1")
    _, job_pre = _run(Q1_MV, "q1a")
    from risingwave_tpu.device.fused import PrecombineNode
    assert any(isinstance(nd, PrecombineNode)
               for nd in job_pre.program.nodes)
    tv_raw = _traffic(job_raw, _flow_node(job_raw))
    tv_pre = _traffic(job_pre, _flow_node(job_pre))
    assert sum(tv_raw) > 0
    assert tv_raw == tv_pre


@pytest.mark.mesh
def test_traffic_sums_shard_invariant(monkeypatch):
    """The acceptance bar: the tv* slots ride `stat_sums`, so
    `sharded_apply` psums them — an 8-shard run's per-bucket totals
    equal the 1-shard run's EXACTLY (hot-key replication off: a
    broadcast row would legitimately count once per shard)."""
    _arm_flow(monkeypatch)
    _, job1 = _run(Q1_MV, "q1a", shards=1)
    _, job8 = _run(Q1_MV, "q1a", shards=8)
    tv1 = _traffic(job1, _flow_node(job1))
    tv8 = _traffic(job8, _flow_node(job8))
    assert sum(tv1) > 0
    assert tv1 == tv8


def test_traffic_divergence_zipf_flow_over_spread_state(monkeypatch):
    """Zipf bidder traffic over the (per-key-once) occupancy profile:
    the hot bucket's traffic share dwarfs its occupancy share — the
    'hot flow over cold state' signal occupancy-driven rebalancing
    cannot see. rw_key_skew alone would call this job balanced."""
    _arm_flow(monkeypatch)
    db, job = _run(Q1_MV, "q1a", kd="zipf:4")
    rows = db.query("SELECT * FROM rw_vnode_traffic WHERE job = 'q1a'")
    div = [r for r in rows if r[3] == "traffic_div"]
    assert div and div[0][6] > 0.1
    skew = [r for r in rows if r[3] == "traffic_skew"]
    assert skew and skew[0][6] > 2.0     # rank-1 bidder dominates
    # the EWMA ring saw at least one checkpoint window (a drained job's
    # final window is legitimately quiet, so only the row is guaranteed)
    burst = [r for r in rows if r[3] == "traffic_burst"]
    assert burst and burst[0][6] >= 0.0 and burst[0][5] > 0


def test_traffic_ewma_burst_vs_sustained():
    from risingwave_tpu.device.skew_stats import SK_BUCKETS, TrafficEwma
    ew = TrafficEwma(alpha=0.3)
    flat = [100] * SK_BUCKETS
    cum = [0] * SK_BUCKETS
    for _ in range(8):                     # sustained uniform flow
        cum = [c + f for c, f in zip(cum, flat)]
        ew.update(cum)
    sustained = ew.burst_ratio()
    assert 0.5 < sustained < 1.5           # converged toward 1
    spike = list(flat)
    spike[3] += 5000                       # one-off burst in bucket 3
    cum = [c + s for c, s in zip(cum, spike)]
    ew.update(cum)
    # the spike is already folded into the EWMA when the ratio reads,
    # so a fresh burst tops out near 1/alpha — still cleanly above the
    # sustained band
    assert ew.burst_ratio() > 2.5
    for _ in range(8):                     # burst decays back
        cum = [c + f for c, f in zip(cum, flat)]
        ew.update(cum)
    assert ew.burst_ratio() < 1.5


def test_flow_unarmed_no_slots_no_sig_flag(monkeypatch):
    """RW_FLOW_STATS=0 (the conftest default) must leave the program
    byte-identical to the pre-feature shape: no `flow` nodes, no tv*
    stat slots, no ('flow',) signature flag — zero fresh compiles for
    every existing cached signature."""
    monkeypatch.setenv("RW_FLOW_STATS", "0")
    _, job = _run(PP_MV, "pp", srcs=(PERSON_SRC,), n=1024)
    assert all(not nd.flow for nd in job.program.nodes)
    assert not any(s.startswith("tv")
                   for _i, s in job.program.stat_layout)
    assert all("flow" not in str(nd._sig())
               for nd in job.program.nodes)
    # armed: the flag and the slots appear
    monkeypatch.setenv("RW_FLOW_STATS", "1")
    _, job2 = _run(PP_MV, "pp", srcs=(PERSON_SRC,), n=1024)
    assert any(nd.flow for nd in job2.program.nodes)
    assert any(s.startswith("tv") for _i, s in job2.program.stat_layout)
    flagged = [nd for nd in job2.program.nodes if nd.flow]
    assert all("flow" in str(nd._sig()) for nd in flagged)


# ---------------------------------------------------------------------------
# tentpole 2: pressure attribution
# ---------------------------------------------------------------------------


def test_combine_contributions_math():
    from risingwave_tpu.utils.overload import (combine_contributions,
                                               dominant_contribution)
    # stall family sums (capped at 1); sink/queue take the max; the
    # combined scalar is the max of the two families
    rows = [("stall", "sink", 0.3), ("stall", "exchange_credit", 0.4),
            ("sink", "snk", 0.2), ("queue", "q:setA", 0.5)]
    assert abs(combine_contributions(rows) - 0.7) < 1e-12
    # dominant = the single loudest source, whatever its family
    assert dominant_contribution(rows) == "queue:q:setA"
    assert dominant_contribution(rows[:2]) == "stall:exchange_credit"
    # stall saturates: the cap lives in the combine, the split stays
    # uncapped so the decomposition remains visible
    rows = [("stall", "a", 0.9), ("stall", "b", 0.8)]
    assert combine_contributions(rows) == 1.0
    assert combine_contributions([]) == 0.0
    assert dominant_contribution([]) == ""


def test_pressure_board_by_kind_windows():
    board_cls = type(PRESSURE)
    b = board_cls()
    now = time.monotonic()
    b.note("sink", 3.0)
    b.note("exchange_credit", 1.0)
    by = b.by_kind(60.0)
    assert by["sink"] == pytest.approx(3.0)
    assert by["exchange_credit"] == pytest.approx(1.0)
    # the scalar is the capped sum over kinds — same events, one cap
    assert b.fraction(60.0) == pytest.approx(
        min(1.0, sum(by.values()) / 60.0))
    assert now is not None


def test_attribution_sums_to_global_slow_sink(tmp_path):
    """overload.slow_sink: the sink stalls, the board fills with stall
    evidence, and the per-source decomposition must recombine to the
    EXACT scalar the ladder saw (same attribution() call feeds both —
    the invariant holds by construction, this pins it)."""
    from risingwave_tpu.utils.overload import combine_contributions
    ROBUSTNESS.overload_hold_s = 0.0
    ROBUSTNESS.overload_window_s = 30.0
    ROBUSTNESS.overload_high, ROBUSTNESS.overload_low = 0.5, 0.1
    db = Database()
    db.run("CREATE TABLE t (k BIGINT, v BIGINT) WITH ("
           "connector='datagen', rows.per.poll='64')")
    path = str(tmp_path / "out.jsonl")
    db.run(f"CREATE SINK snk FROM t WITH (connector='fs',"
           f" fs.path='{path}', format='jsonl')")
    fp.arm("overload.slow_sink", 1.0, 0, None)
    for _ in range(6):
        db.tick()
        time.sleep(0.01)
    m = db._overload
    assert m.last_attribution, "stalled sink must attribute"
    assert m.last_pressure == combine_contributions(m.last_attribution)
    assert m.last_pressure > 0.0
    assert m.last_dominant != ""
    fams = {f for f, _s, _v in m.last_attribution}
    assert "sink" in fams or "stall" in fams
    # the system-table surface: per-source rows + the combined row,
    # exactly one row flagged dominant
    rows = db.query("SELECT * FROM rw_pressure_attrib")
    combined = [r for r in rows if r[0] == "combined"]
    assert len(combined) == 1
    assert combined[0][2] == pytest.approx(m.last_pressure)
    assert sum(1 for r in rows if r[3]) == 1
    dom = next(r for r in rows if r[3])
    assert f"{dom[0]}:{dom[1]}" == m.last_dominant
    # rw_overload names WHY each rung was taken
    ov = db.query("SELECT * FROM rw_overload WHERE job = 'snk'")
    assert ov and any(r[1] > 0 for r in ov), "transitions recorded"
    assert all(len(r) == 9 for r in ov)
    assert any(r[8] != "" for r in ov if r[1] > 0), \
        "transitions must carry dominant_source"


def test_attribution_sums_to_global_slow_worker(monkeypatch):
    """overload.slow_worker (armed in the workers via the environment):
    exchange credit starvation feeds stall evidence; the decomposition
    must name a stall source and recombine exactly. Bounded: the test
    needs the evidence, not job completion."""
    from risingwave_tpu.utils.overload import combine_contributions
    monkeypatch.setenv("RW_FAILPOINTS", "overload.slow_worker:1")
    ROBUSTNESS.overload_window_s = 2.0
    ROBUSTNESS.overload_high, ROBUSTNESS.overload_low = 0.15, 0.05
    ROBUSTNESS.overload_hold_s = 0.0
    ROBUSTNESS.exchange_credits = 4
    db = Database()
    db.run("SET streaming_parallelism = 2")
    db.run("SET streaming_placement TO process")
    db.run(BID_SRC.format(n=4000, c=64, kd="zipf:2"))
    db.run("CREATE MATERIALIZED VIEW q AS SELECT bidder,"
           " count(*) AS cnt FROM bid GROUP BY bidder")
    try:
        deadline = time.monotonic() + 45.0
        m = db._overload
        seen_stall = False
        while time.monotonic() < deadline:
            db.tick()
            if m.last_attribution:
                assert m.last_pressure == \
                    combine_contributions(m.last_attribution)
            if any(f == "stall" and v > 0
                   for f, _s, v in m.last_attribution):
                seen_stall = True
                break
        assert seen_stall, "credit starvation must attribute as stall"
        assert m.last_dominant != ""
    finally:
        from risingwave_tpu.sql.database import _walk_executors
        for obj in db.catalog.objects.values():
            rt = obj.runtime if isinstance(obj.runtime, dict) else None
            if rt and rt.get("shared") is not None:
                for e in _walk_executors(rt["shared"].upstream):
                    r = getattr(e, "_remote", None)
                    if r is not None:
                        r.shutdown()


# ---------------------------------------------------------------------------
# tentpole 3: flight recorder
# ---------------------------------------------------------------------------


def test_blackbox_auto_dump_and_offline_read(tmp_path, capsys,
                                             monkeypatch):
    """A seeded device fault (fused.dispatch) drives an in-place
    recovery, which auto-dumps a bundle; the dead directory then yields
    the ring + bundles to `risectl blackbox` with no process, and the
    chrome export carries the recovery as an instant event."""
    from risingwave_tpu import ctl
    from risingwave_tpu.utils.blackbox import (RECORDER, RING_FILE,
                                               list_bundles, read_bundle)
    RECORDER._last_dump.clear()        # earlier tests may have primed
    monkeypatch.setenv("RW_FLOW_STATS", "1")
    d = str(tmp_path / "d")
    db = Database(device=DeviceConfig(capacity=2048, aot_compile=False,
                                      compile_buckets=0),
                  data_dir=d)
    db.run(BID_SRC.format(n=N, c=CHUNK, kd="zipf:2"))
    db.run(Q1_MV)
    job = db.catalog.get("q1a").runtime["fused_job"]
    db.tick()
    fp.arm("fused.dispatch", 1.0, 0, 1)
    for _ in range(N // (64 * CHUNK) + 3):
        db.tick()
    fp.reset()
    job.sync()
    db.tick()
    assert job.recoveries >= 1, "the seeded fault must recover in place"
    # the always-on ring mirrored to disk...
    assert os.path.getsize(os.path.join(d, RING_FILE)) > 0
    # ...and the recovery auto-dumped a bundle
    bundles = list_bundles(d)
    assert bundles, "in-place recovery must auto-dump"
    name, manifest = bundles[-1]
    assert "in_place_recovery" in name
    assert manifest["schema"] == 1 and manifest["records"] > 0
    recs = read_bundle(d, name)
    kinds = {r["kind"] for r in recs}
    assert "recovery" in kinds and "boot" in kinds
    rec = next(r for r in recs if r["kind"] == "recovery")
    assert rec["job"] == "q1a" and rec["error"] and rec["wall_s"] >= 0
    # ---- the directory is now DEAD ----------------------------------
    del db, job
    assert ctl.main(["blackbox", "list", "--data-dir", d]) == 0
    out = capsys.readouterr().out
    assert "in_place_recovery" in out and "recovery" in out
    assert ctl.main(["blackbox", "dump", "--data-dir", d,
                     "--reason", "postmortem"]) == 0
    assert "postmortem" in capsys.readouterr().out
    post = list_bundles(d)
    assert len(post) == len(bundles) + 1
    assert ctl.main(["blackbox", "show", post[-1][0],
                     "--data-dir", d]) == 0
    shown = [json.loads(ln) for ln in
             capsys.readouterr().out.splitlines() if ln.strip()]
    assert any(r.get("kind") == "recovery" for r in shown)
    # a dir with no ring file degrades gracefully
    assert ctl.main(["blackbox", "dump",
                     "--data-dir", str(tmp_path)]) == 1
    # ---- chrome export with the new instant events ------------------
    from risingwave_tpu.utils.export import export_chrome, validate_chrome
    doc = export_chrome(d)
    assert validate_chrome(doc) == []
    instants = [e for e in doc["traceEvents"]
                if e["ph"] == "i" and e["pid"] == "control"]
    assert any(e["tid"] == "recovery" for e in instants)


def test_blackbox_ring_byte_bound_and_rate_limit(tmp_path):
    from risingwave_tpu.utils.blackbox import FlightRecorder
    r = FlightRecorder(max_bytes=2048)
    r.attach(str(tmp_path))
    for i in range(500):
        r.record("epoch", {"seq_no": i, "pad": "x" * 32})
    st = r.stats()
    assert st["bytes"] <= 2048 and st["dropped"] > 0
    assert st["records"] < 500
    # first auto-dump lands; an immediate retrigger of the SAME reason
    # coalesces; a DIFFERENT reason still dumps
    assert r.maybe_dump("wedge_reap") is not None
    assert r.maybe_dump("wedge_reap") is None
    assert r.maybe_dump("quarantine") is not None
    # unattached recorders record but cannot dump — and never raise
    lone = FlightRecorder()
    lone.record("epoch", {"x": object()})     # unserializable: fallback
    assert lone.dump("manual") is None
    assert lone.stats()["records"] == 1


# ---------------------------------------------------------------------------
# satellites: epoch-profile schema, served staleness, replica pulls,
# dead-telemetry lint
# ---------------------------------------------------------------------------


def test_profile_schema_dispatch(tmp_path):
    from risingwave_tpu.utils.profile import (PROFILE_SCHEMA,
                                              decode_epoch,
                                              summarize_file)
    assert PROFILE_SCHEMA >= 2
    # schema-1 records fold host_pack into pack; schema-2 pass through
    assert decode_epoch({"ph_ms": {"pack": 1.0, "host_pack": 2.0}}
                        ) == {"pack": 3.0}
    assert decode_epoch({"schema": 2,
                         "ph_ms": {"pack": 1.0, "host_pack": 2.0}}
                        ) == {"pack": 1.0, "host_pack": 2.0}
    # a mixed-version file summarizes on one decode path
    path = str(tmp_path / "epoch_profile.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"ev": "epoch", "job": "j", "seq": 1,
                            "events": 10, "wall_ms": 5.0,
                            "ph_ms": {"pack": 1.0, "host_pack": 2.0,
                                      "dispatch": 1.0}}) + "\n")
        f.write(json.dumps({"ev": "epoch", "schema": 2, "job": "j",
                            "seq": 2, "events": 10, "wall_ms": 4.0,
                            "ph_ms": {"pack": 2.5,
                                      "dispatch": 1.0}}) + "\n")
    out = summarize_file(path)
    assert out["j"]["epochs"] == 2
    assert out["j"]["phase_ms"]["pack"] == pytest.approx(5.5)
    assert "host_pack" not in out["j"]["phase_ms"]


def test_served_staleness_reported_for_cache_lagged_selects(monkeypatch):
    """The fix under test: a SELECT served from a cache snapshot OLDER
    than the last commit must surface the staleness the reader actually
    experienced in rw_mv_freshness — not the store's head freshness."""
    monkeypatch.setenv("RW_FLOW_STATS", "0")
    n = 4 * N                              # stream outlives the fill
    db = Database(device=DeviceConfig(capacity=4096, aot_compile=False,
                                      compile_buckets=0))
    db.run(BID_SRC.format(n=n, c=CHUNK, kd="zipf:2"))
    db.run(Q1_MV)
    job = db.catalog.get("q1a").runtime["fused_job"]
    db.tick()
    # a huge staleness budget pins the cache to its first snapshot
    # while the rest of the stream commits past it
    ROBUSTNESS.serving_staleness_epochs = 10_000
    assert db.query("SELECT * FROM q1a") is not None   # early fill
    fill_ts = db.read_cache.fill_time("q1a")
    assert fill_ts is not None
    for _ in range(n // (64 * CHUNK) + 3):
        db.tick()
    job.sync()
    db.tick()
    assert int(job.counter) > db.read_cache._entries["q1a"].epoch, \
        "commits must outrun the cached snapshot"
    db.query("SELECT * FROM q1a")                 # SERVED stale
    assert "q1a" in db._freshness._served
    row = next(r for r in db._freshness.rows() if r[0] == "q1a")
    # anchored at (or before) the snapshot's fill time, never the head
    assert row[5] >= time.time() - fill_ts - 0.5
    assert len(row) == 9                          # shape unchanged
    # an up-to-date serve clears the marker
    ROBUSTNESS.serving_staleness_epochs = 0
    db.query("SELECT * FROM q1a")
    assert "q1a" not in db._freshness._served


def test_rw_serving_pulls_and_replica_metric(monkeypatch):
    from risingwave_tpu.device.shard_exec import (PULL_STATS,
                                                  reset_pull_stats)
    from risingwave_tpu.utils.metrics import REGISTRY
    monkeypatch.setenv("RW_FLOW_STATS", "0")
    reset_pull_stats()
    db, _job = _run(Q1_MV, "q1a", n=2048)
    assert db.query("SELECT * FROM q1a")
    rows = db.query("SELECT * FROM rw_serving_pulls")
    total = next(r for r in rows if r[0] == -1)
    assert total[1] == PULL_STATS["device_pulls"] >= 1
    per_rep = [r for r in rows if r[0] >= 0]
    assert per_rep and sum(r[1] for r in per_rep) == total[1]
    exp = REGISTRY.expose()
    assert "serving_device_pulls_total" in exp
    assert "serving_replica_pulls_total" in exp


def test_dead_telemetry_lint():
    from risingwave_tpu.utils.metrics import (MetricsRegistry,
                                              dead_telemetry)
    reg = MetricsRegistry()
    reg.counter("live_total", "instantiated", labels=("job",)
                ).labels("j").inc()
    reg.counter("dead_total", "declared, never labeled", labels=("job",))
    reg.counter("plain_total", "unlabeled metrics are exempt").inc()
    flagged = dead_telemetry(reg)
    assert any("dead_total" in p for p in flagged)
    assert not any("live_total" in p for p in flagged)
    assert not any("plain_total" in p for p in flagged)
