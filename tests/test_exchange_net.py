"""Cross-process exchange: wire encoding, credit flow control, and the
two-process Nexmark q4 demo (VERDICT r02 item 4)."""
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from risingwave_tpu.core import dtypes as T
from risingwave_tpu.core.chunk import Op, StreamChunk
from risingwave_tpu.core.epoch import EpochPair
from risingwave_tpu.ops.message import Barrier, BarrierKind, Watermark
from risingwave_tpu.runtime.exchange_net import (DEFAULT_PERMITS,
                                                 ExchangeServer, RemoteInput,
                                                 decode_message,
                                                 encode_message)


def test_wire_roundtrip_chunk_barrier_watermark():
    dtypes = [T.INT64, T.VARCHAR, T.TIMESTAMP, T.DECIMAL]
    from decimal import Decimal
    rows = [(Op.INSERT, (1, "a", 1_700_000_000_000_000, Decimal("1.25"))),
            (Op.UPDATE_DELETE, (2, None, 5, None)),
            (Op.UPDATE_INSERT, (2, "b''x", 6, Decimal("-3"))),
            (Op.DELETE, (3, "", 7, Decimal("0")))]
    chunk = StreamChunk.from_rows(dtypes, rows)
    tag, body = encode_message(chunk, dtypes)
    back = decode_message(tag, body, dtypes)
    assert [(op, r) for op, r in back.compact().op_rows()] == rows

    b = Barrier(EpochPair(7 << 16, 6 << 16), BarrierKind.CHECKPOINT)
    tag, body = encode_message(b, dtypes)
    b2 = decode_message(tag, body, dtypes)
    assert b2.epoch == b.epoch and b2.kind == b.kind and not b2.is_stop()

    w = Watermark(2, T.TIMESTAMP, 123_456)
    tag, body = encode_message(w, dtypes)
    w2 = decode_message(tag, body, dtypes)
    assert (w2.col_idx, w2.value) == (2, 123_456)
    assert w2.dtype.kind == T.TIMESTAMP.kind


def test_credit_backpressure_blocks_sender():
    """The writer must stop at the permit budget until the receiver
    grants more credit (permit.rs semantics)."""
    dtypes = [T.INT64]
    server = ExchangeServer()
    ch = server.register(0, dtypes)
    n_send = DEFAULT_PERMITS + 50
    for i in range(n_send):
        ch.send(StreamChunk.from_rows(dtypes, [(Op.INSERT, (i,))]))
    ch.close()
    sock = socket.create_connection(server.addr)
    sock.sendall(struct.pack(">I", 3) + b"H" + struct.pack(">H", 0))
    # consume WITHOUT granting permits: exactly DEFAULT_PERMITS chunks
    # arrive, then the stream stalls
    got = 0
    sock.settimeout(1.0)

    def recv_frame():
        hdr = b""
        while len(hdr) < 4:
            hdr += sock.recv(4 - len(hdr))
        (ln,) = struct.unpack(">I", hdr)
        body = b""
        while len(body) < ln:
            body += sock.recv(ln - len(body))
        return body[:1], body[1:]

    try:
        while True:
            tag, _ = recv_frame()
            if tag in (b"C", b"K"):    # chunks ride the columnar K frame
                got += 1
    except socket.timeout:
        pass
    assert got == DEFAULT_PERMITS
    # grant credit; the rest (+ EOS) flows
    sock.sendall(struct.pack(">I", 5) + b"P" + struct.pack(">I", 1000))
    done = False
    while not done:
        tag, _ = recv_frame()
        if tag in (b"C", b"K"):
            got += 1
        elif tag == b"E":
            done = True
    assert got == n_send
    sock.close()
    server.close()


N_EVENTS = 20_000
CHUNK = 256
K = 3


def _consume_q4(addr):
    """Process B: K remote fragments -> HashAgg -> aligned Merge -> MV."""
    from risingwave_tpu.expr.agg import AggCall
    from risingwave_tpu.expr.expression import InputRef
    from risingwave_tpu.ops import (Channel, HashAggExecutor, MergeExecutor,
                                    ProjectExecutor)
    from risingwave_tpu.ops.exchange import FragmentPump
    from risingwave_tpu.runtime.exchange_demo import BID_SCHEMA

    pumps, outs = [], []
    for i in range(K):
        remote = RemoteInput(addr, i, BID_SCHEMA, append_only=True)
        proj = ProjectExecutor(remote,
                               [InputRef(0, T.INT64), InputRef(2, T.INT64)],
                               ["auction", "price"])
        price = InputRef(1, T.INT64)
        agg = HashAggExecutor(proj, [0],
                              [AggCall("count"), AggCall("sum", price),
                               AggCall("max", price)])
        out = Channel(capacity=1 << 20)
        pumps.append(FragmentPump(agg, out))
        outs.append(out)
    merge = MergeExecutor(outs, pumps[0].execu.schema, pumps=pumps)
    mv = {}
    for msg in merge.execute():
        if isinstance(msg, StreamChunk):
            for op, r in msg.compact().op_rows():
                if op.is_insert:
                    mv[r[0]] = r[1:]
                else:
                    if mv.get(r[0]) == r[1:]:
                        del mv[r[0]]
        elif isinstance(msg, Barrier) and msg.is_stop():
            break
    return mv


def test_two_process_nexmark_q4_parity():
    """Process A (subprocess): source + hash dispatch + exchange server.
    Process B (here): remote inputs + aggs + merge. The MV must equal the
    single-process SQL run over the same generator."""
    # pick a free port
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    proc = subprocess.Popen(
        [sys.executable, "-m", "risingwave_tpu.runtime.exchange_demo",
         "producer", str(port), str(N_EVENTS), str(K), str(CHUNK)],
        cwd="/root/repo",
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": "/root/repo"})
    try:
        deadline = time.time() + 60
        while time.time() < deadline:
            try:
                socket.create_connection(("127.0.0.1", port),
                                         timeout=0.2).close()
                break
            except OSError:
                time.sleep(0.2)
        mv = _consume_q4(("127.0.0.1", port))
    finally:
        proc.terminate()
        proc.wait(timeout=30)

    # single-process oracle: the same q4 through SQL
    from risingwave_tpu.sql import Database
    db = Database()
    db.run("CREATE SOURCE bid (auction BIGINT, bidder BIGINT, price BIGINT,"
           " channel VARCHAR, url VARCHAR, date_time TIMESTAMP,"
           " extra VARCHAR) WITH (connector='nexmark',"
           f" nexmark.table='bid', nexmark.max.events='{N_EVENTS}',"
           f" nexmark.chunk.size='{CHUNK}')")
    db.run("CREATE MATERIALIZED VIEW q4 AS SELECT auction, count(*) AS c,"
           " sum(price) AS s, max(price) AS m FROM bid GROUP BY auction")
    for _ in range(N_EVENTS // (64 * CHUNK) + 3):
        db.tick()
    want = {r[0]: tuple(r[1:]) for r in db.query("SELECT * FROM q4")}
    assert len(mv) == len(want) > 50
    assert mv == want
